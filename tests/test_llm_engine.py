"""Paged KV cache + continuous-batching engine + @serve.batch.

The reference's serving parity story is vLLM-on-Ray (SURVEY §2.9); these
tests cover the native replacements: block-paged decode matching the
contiguous-cache reference path, iteration-level admission, recompute
preemption, and the serve.batch queue
(reference: python/ray/serve/batching.py:468).
"""
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models.generate import generate
from ray_tpu.models.paged import PagedConfig
from ray_tpu.models.transformer import TransformerConfig, init_params
from ray_tpu.serve.llm_engine import LLMEngine


@pytest.fixture(autouse=True)
def _highest_precision():
    """Token-for-token assertions compare two differently-shaped
    computations of the same math; run the whole module at fp32 matmul
    precision so rounding can't flip an argmax (see conftest note)."""
    prev = jax.config.jax_default_matmul_precision
    jax.config.update("jax_default_matmul_precision", "highest")
    yield
    jax.config.update("jax_default_matmul_precision", prev)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = TransformerConfig.tiny(dtype=jnp.float32, remat=False)
    params = init_params(jax.random.PRNGKey(7), cfg)
    params = jax.tree.map(lambda x: jax.device_put(x), params)
    return cfg, params


def _engine(cfg, params, **kw):
    pcfg = PagedConfig(**{**dict(block_size=8, num_blocks=33, max_batch=4,
                                 max_blocks_per_seq=8), **kw})
    return LLMEngine(params, cfg, pcfg)


def test_paged_decode_matches_contiguous_generate(tiny_model):
    """Greedy paged decode must match the contiguous-cache generate()
    path token for token (same math, different memory layout)."""
    cfg, params = tiny_model
    eng = _engine(cfg, params)
    prompts = [[5, 9, 2, 11, 3], [17, 1, 8], [30, 31, 32, 33, 34, 35, 36]]
    outs = eng.generate_batch(prompts, max_new_tokens=12)
    for p, o in zip(prompts, outs):
        ref = generate(params, cfg, jnp.asarray([p], jnp.int32), 12)
        assert o == list(np.asarray(ref[0])), f"prompt {p}"
    assert eng.stats["max_active"] == 3
    assert eng.stats["preemptions"] == 0


def test_continuous_admission_more_requests_than_slots(tiny_model):
    """8 requests through 4 slots: retired slots must be refilled from
    the waiting queue mid-flight (iteration-level scheduling)."""
    cfg, params = tiny_model
    eng = _engine(cfg, params, max_batch=4)
    prompts = [[i + 1, i + 2, i + 3] for i in range(8)]
    outs = eng.generate_batch(prompts, max_new_tokens=6)
    assert all(len(o) == 6 for o in outs)
    assert eng.stats["max_active"] == 4  # saturated
    assert eng.stats["prefills"] == 8


def test_preemption_recompute_completes(tiny_model):
    """A pool too small for all sequences forces eviction; evicted
    requests must resume via re-prefill and still finish."""
    cfg, params = tiny_model
    # 12 usable blocks * 8 = 96 cache tokens; 4 seqs * (4 + 28) = 128
    # tokens needed at full length → somebody must get preempted.
    eng = _engine(cfg, params, num_blocks=13, max_batch=4, max_blocks_per_seq=4)
    prompts = [[i + 1, i + 2, i + 3, i + 4] for i in range(4)]
    outs = eng.generate_batch(prompts, max_new_tokens=28)
    assert all(len(o) == 28 for o in outs)
    assert eng.stats["preemptions"] > 0
    # Preempted-and-resumed greedy decode must agree with an unpressured
    # run of the same prompt.
    calm = _engine(cfg, params)
    calm_outs = calm.generate_batch(prompts, max_new_tokens=28)
    assert outs == calm_outs


def test_eos_stops_early(tiny_model):
    cfg, params = tiny_model
    eng = _engine(cfg, params)
    [out] = eng.generate_batch([[3, 1, 4, 1, 5]], max_new_tokens=10)
    assert len(out) == 10
    # Pick an actually-produced token whose FIRST occurrence is
    # mid-stream (a repeated token would legitimately stop earlier).
    k = next((k for k in range(1, 10) if out.index(out[k]) == k), None)
    if k is None:
        pytest.skip("greedy output degenerated to pure repetition")
    eos = out[k]
    eng2 = _engine(cfg, params)
    [out2] = eng2.generate_batch([[3, 1, 4, 1, 5]], max_new_tokens=10, eos_id=eos)
    assert out2 == out[: k + 1]  # stops AT the eos token


def test_request_rejected_when_too_long(tiny_model):
    cfg, params = tiny_model
    eng = _engine(cfg, params)  # max_seq_len = 64
    req = eng.add_request([1] * 60, max_new_tokens=10)
    with pytest.raises(RuntimeError, match="exceeds capacity"):
        list(req.tokens(timeout=5))


def test_streaming_two_clients_share_one_batch(tiny_model):
    """Two concurrent clients stream tokens from the SAME decode batch —
    the engine pump thread serves both; token timelines interleave."""
    cfg, params = tiny_model
    eng = _engine(cfg, params)
    eng.start()
    try:
        results = {}

        def client(name, prompt):
            req = eng.add_request(prompt, max_new_tokens=16)
            toks = []
            for t in req.tokens(timeout=60):
                toks.append((t, time.monotonic()))
            results[name] = toks

        t1 = threading.Thread(target=client, args=("a", [2, 4, 6]))
        t2 = threading.Thread(target=client, args=("b", [1, 3, 5, 7]))
        t1.start(); t2.start(); t1.join(60); t2.join(60)
        assert len(results["a"]) == 16 and len(results["b"]) == 16
        assert eng.stats["max_active"] == 2  # truly shared a batch
        # Interleaved in time: a's stream starts before b's ends and
        # vice versa (not serial execution).
        a_times = [ts for _, ts in results["a"]]
        b_times = [ts for _, ts in results["b"]]
        assert a_times[0] < b_times[-1] and b_times[0] < a_times[-1]
    finally:
        eng.stop()


def test_serve_batch_decorator_batches_concurrent_calls():
    from ray_tpu.serve.batching import batch

    calls = []

    class Model:
        @batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def predict(self, items):
            calls.append(list(items))
            return [x * 10 for x in items]

    m = Model()
    results = {}
    threads = [
        threading.Thread(target=lambda i=i: results.__setitem__(i, m.predict(i)))
        for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert results == {0: 0, 1: 10, 2: 20, 3: 30}
    # All four went through in one (or at most two) underlying calls.
    assert len(calls) <= 2
    assert sum(len(c) for c in calls) == 4


def test_serve_batch_propagates_errors_and_size_mismatch():
    from ray_tpu.serve.batching import batch

    @batch(max_batch_size=2, batch_wait_timeout_s=0.05)
    def bad(items):
        return [1]  # wrong length on a 2-batch, right length on a 1-batch

    @batch(max_batch_size=1, batch_wait_timeout_s=0.01)
    def boom(items):
        raise RuntimeError("kaput")

    with pytest.raises(RuntimeError, match="kaput"):
        boom(1)
    # Single call → length-1 batch → valid.
    assert bad(5) == 1


def test_empty_prompt_rejected_and_pool_not_drained(tiny_model):
    """Regression: alloc(0) must not hand out the whole free list."""
    cfg, params = tiny_model
    eng = _engine(cfg, params)
    free_before = eng.alloc.available
    req = eng.add_request([], max_new_tokens=4)
    with pytest.raises(RuntimeError, match="non-empty"):
        list(req.tokens(timeout=5))
    assert eng.alloc.available == free_before
    # And a zero-alloc is an empty list, not the pool.
    assert eng.alloc.alloc(0) == []
    assert eng.alloc.available == free_before


def test_windowed_decode_matches_window1(tiny_model):
    """decode_window > 1 (multi-step scan per device call) must be
    token-for-token identical to per-step decode under greedy sampling,
    including eos mid-window and slot refill afterwards."""
    cfg, params = tiny_model
    prompts = [[5, 9, 2], [17, 1, 8, 4], [30, 31], [7, 6, 5, 4, 3]]
    base = _engine(cfg, params).generate_batch(prompts, max_new_tokens=13)
    eng_w = LLMEngine(
        params, cfg,
        PagedConfig(block_size=8, num_blocks=33, max_batch=2, max_blocks_per_seq=8),
        decode_window=4,
    )
    outs = eng_w.generate_batch(prompts, max_new_tokens=13)
    assert outs == base
    # 2 slots served 4 requests → retirement + refill at window seams.
    assert eng_w.stats["prefills"] == 4 and eng_w.stats["max_active"] == 2
    # eos mid-window stops exactly at the eos token.
    eos = base[0][5]
    eng_e = _engine(cfg, params, max_batch=4)
    eng_we = LLMEngine(
        params, cfg,
        PagedConfig(block_size=8, num_blocks=33, max_batch=4, max_blocks_per_seq=8),
        decode_window=4,
    )
    [e1] = eng_e.generate_batch([prompts[0]], max_new_tokens=13, eos_id=eos)
    [e2] = eng_we.generate_batch([prompts[0]], max_new_tokens=13, eos_id=eos)
    assert e1 == e2 and e1[-1] == eos


def test_overlap_decode_matches_synchronous(tiny_model):
    """Host/device overlap (window N+1 dispatched before N's tokens are
    read) must be token-for-token identical to synchronous stepping —
    including eos mid-window and slot retirement/refill at seams."""
    cfg, params = tiny_model
    prompts = [[5, 9, 2], [17, 1, 8, 4], [30, 31], [7, 6, 5, 4, 3]]
    for w in (1, 4):
        base = LLMEngine(
            params, cfg,
            PagedConfig(block_size=8, num_blocks=33, max_batch=4,
                        max_blocks_per_seq=8),
            decode_window=w,
        ).generate_batch(prompts, max_new_tokens=12)
        eng_o = LLMEngine(
            params, cfg,
            PagedConfig(block_size=8, num_blocks=33, max_batch=4,
                        max_blocks_per_seq=8),
            decode_window=w, overlap=True,
        )
        assert eng_o.generate_batch(prompts, max_new_tokens=12) == base
        # The point of overlap: most windows dispatched speculatively.
        assert eng_o.stats["spec_windows"] > 0
        # eos stops exactly at the eos token under speculation too (pick
        # a token whose FIRST occurrence is mid-stream, not a repeat).
        k = next(
            (k for k in range(1, 12) if base[0].index(base[0][k]) == k), None
        )
        if k is None:
            pytest.skip("greedy output degenerated to pure repetition")
        eos = base[0][k]
        eng_e = LLMEngine(
            params, cfg,
            PagedConfig(block_size=8, num_blocks=33, max_batch=4,
                        max_blocks_per_seq=8),
            decode_window=w, overlap=True,
        )
        [e] = eng_e.generate_batch([prompts[0]], max_new_tokens=12, eos_id=eos)
        assert e == base[0][: k + 1] and e[-1] == eos


def test_overlap_preemption_under_pressure(tiny_model):
    """Preempting a slot whose speculated window is still in flight must
    not corrupt any stream: the stale window's lanes are discarded (rid
    check) and the victim resumes to an identical greedy output."""
    cfg, params = tiny_model
    prompts = [[i + 1, i + 2, i + 3, i + 4] for i in range(4)]
    calm = _engine(cfg, params).generate_batch(prompts, max_new_tokens=24)
    eng = LLMEngine(
        params, cfg,
        PagedConfig(block_size=8, num_blocks=13, max_batch=4,
                    max_blocks_per_seq=4),
        decode_window=2, overlap=True,
    )
    outs = eng.generate_batch(prompts, max_new_tokens=24)
    assert outs == calm
    assert eng.stats["preemptions"] > 0
