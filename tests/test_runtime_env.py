"""Runtime environments: env_vars, working_dir, py_modules, worker affinity.

Reference test models: python/ray/tests/test_runtime_env.py,
test_runtime_env_env_vars.py, test_runtime_env_working_dir.py.
"""
import os

import pytest

import ray_tpu
from ray_tpu.exceptions import RuntimeEnvSetupError, TaskError
from ray_tpu.runtime_env import RuntimeEnv, env_hash

from conftest import shared_cluster_fixtures

# Shared cluster for the whole file (suite-time headroom): runtime-env
# worker affinity is keyed by env hash, so cached env workers from
# earlier tests route correctly for later ones.
ray_start_regular, _shared_cluster_guard = shared_cluster_fixtures(
    num_cpus=16, resources={"TPU": 4}
)



def test_runtime_env_validation():
    e = RuntimeEnv(env_vars={"A": "1"}, working_dir="/tmp")
    assert e["env_vars"] == {"A": "1"}
    with pytest.raises(ValueError):
        RuntimeEnv(env_vars={"A": 1})
    with pytest.raises(ValueError):
        RuntimeEnv(bogus_key=1)
    assert env_hash({}) == ""
    assert env_hash({"env_vars": {"A": "1"}}) == env_hash({"env_vars": {"A": "1"}})
    assert env_hash({"env_vars": {"A": "1"}}) != env_hash({"env_vars": {"A": "2"}})
    assert env_hash({"__actor_name__": "x"}) == ""


def test_env_vars_applied(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"MY_TEST_VAR": "hello"}})
    def read_var():
        return os.environ.get("MY_TEST_VAR")

    assert ray_tpu.get(read_var.remote()) == "hello"


def test_env_isolation_across_envs(ray_start_regular):
    """Tasks in different envs must not share a worker."""

    @ray_tpu.remote(runtime_env={"env_vars": {"WHICH": "a"}})
    def env_a():
        return os.environ.get("WHICH"), os.getpid()

    @ray_tpu.remote(runtime_env={"env_vars": {"WHICH": "b"}})
    def env_b():
        return os.environ.get("WHICH"), os.getpid()

    @ray_tpu.remote
    def vanilla():
        return os.environ.get("WHICH"), os.getpid()

    a = [ray_tpu.get(env_a.remote()) for _ in range(3)]
    b = [ray_tpu.get(env_b.remote()) for _ in range(3)]
    v = [ray_tpu.get(vanilla.remote()) for _ in range(3)]
    assert all(x[0] == "a" for x in a)
    assert all(x[0] == "b" for x in b)
    # Vanilla tasks never observe either env.
    assert all(x[0] is None for x in v)
    # Envs never share a worker pid.
    assert {x[1] for x in a}.isdisjoint({x[1] for x in b})
    assert {x[1] for x in v}.isdisjoint({x[1] for x in a} | {x[1] for x in b})


def test_working_dir_and_py_modules(ray_start_regular, tmp_path):
    pkg = tmp_path / "mypkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("MAGIC = 77\n")
    (tmp_path / "data.txt").write_text("payload")

    @ray_tpu.remote(
        runtime_env={"working_dir": str(tmp_path), "py_modules": [str(pkg)]}
    )
    def use_env():
        import mypkg

        with open("data.txt") as f:
            return mypkg.MAGIC, f.read()

    assert ray_tpu.get(use_env.remote()) == (77, "payload")


def test_actor_runtime_env(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"ACTOR_VAR": "yes"}})
    class A:
        def read(self):
            return os.environ.get("ACTOR_VAR")

    a = A.remote()
    assert ray_tpu.get(a.read.remote()) == "yes"


def test_pip_local_package_env(ray_start_regular, tmp_path):
    """runtime_env={"pip": [...]} installs LOCAL packages into a cached
    per-hash --target dir on the worker (reference:
    _private/runtime_env/pip.py; offline-capable — hermetic TPU images
    have no package index)."""
    pkg = tmp_path / "minipkg"
    (pkg / "minipkg_rt").mkdir(parents=True)
    (pkg / "minipkg_rt" / "__init__.py").write_text("MAGIC = 'rt-pip-41'\n")
    (pkg / "setup.py").write_text(
        "from setuptools import setup, find_packages\n"
        "setup(name='minipkg-rt', version='0.1', packages=find_packages())\n"
    )

    @ray_tpu.remote(runtime_env={"pip": [str(pkg)]})
    def use_it():
        import minipkg_rt

        return minipkg_rt.MAGIC

    assert ray_tpu.get(use_it.remote(), timeout=120) == "rt-pip-41"
    # second task reuses the cached env (same hash, no reinstall)
    assert ray_tpu.get(use_it.remote(), timeout=60) == "rt-pip-41"


def test_pip_missing_package_fails(ray_start_regular):
    @ray_tpu.remote(runtime_env={"pip": ["/definitely/not/a/package"]})
    def f():
        return 1

    with pytest.raises((RuntimeEnvSetupError, TaskError)):
        ray_tpu.get(f.remote(), timeout=120)


def test_jax_profiler_captures_trace(ray_start_regular):
    """runtime_env={"jax_profiler": True} captures a jax.profiler trace
    around a jitted task, stored in the session dir and listed via the
    state API + fetched by the CLI (reference: the nsight runtime-env
    plugin, _private/runtime_env/nsight.py)."""

    @ray_tpu.remote(num_cpus=1, runtime_env={"jax_profiler": True})
    def jitted(n):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: (x @ x.T).sum())
        return float(f(jnp.ones((n, n))))

    assert ray_tpu.get(jitted.remote(32), timeout=120) == 32.0 * 32 * 32

    from ray_tpu.util import state

    rows = state.list_profiles()
    mine = [r for r in rows if r.get("name", "").startswith("jitted")]
    assert mine, rows
    row = mine[-1]
    assert row.get("task_id") and row.get("duration_s") is not None
    info = state.get_profile(row["id"])
    # a real capture has xplane/trace payload files beside the metadata
    payload = [f for f in info["files"] if not f.endswith("profile.json")]
    assert payload, info["files"]

    # CLI fetch
    import subprocess
    import sys as _sys

    env = dict(os.environ)
    from ray_tpu.core.api import _require_worker
    env["RAY_TPU_ADDRESS"] = _require_worker().address
    r = subprocess.run(
        [_sys.executable, "-m", "ray_tpu.scripts.cli", "profile", row["id"]],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert r.returncode == 0, r.stderr
    assert row["id"] in r.stdout and "profile.json" in r.stdout


def test_jax_profiler_rejects_bad_options(ray_start_regular):
    @ray_tpu.remote(num_cpus=1, runtime_env={"jax_profiler": {"bogus": 1}})
    def f():
        return 1

    with pytest.raises((RuntimeEnvSetupError, TaskError)):
        ray_tpu.get(f.remote(), timeout=60)
