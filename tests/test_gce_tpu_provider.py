"""GCE/GKE TPU pod-slice provider (reference:
python/ray/autoscaler/_private/gcp/node_provider.py TPU path).

The fake GCE API boots one REAL node agent per slice host, so these
tests drive the v2 InstanceManager FSM against genuinely-joining nodes:
QUEUED → REQUESTED → ALLOCATED → RAY_RUNNING for a 2-host v5e-16 slice,
gang semantics (all hosts appear/die together), and drain termination.
"""
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler.gce_tpu_provider import (
    FakeGceTpuApi,
    GceTpuNodeProvider,
    _slice_shape,
)
from ray_tpu.autoscaler.v2 import InstanceManager, InstanceStatus
from ray_tpu.core.cluster_utils import Cluster

NODE_TYPES = {
    "tpu_v5e_16": {
        "resources": {"CPU": 2},
        "accelerator_type": "v5e-16",
        "min_workers": 0,
        "max_workers": 2,
    }
}


def test_slice_shape():
    assert _slice_shape("v5e-16") == (2, 8)  # 2 hosts x 8 chips
    assert _slice_shape("v5e-8") == (1, 8)
    assert _slice_shape("v4-16") == (4, 4)  # 16 chips = 4 hosts x 4


def _alive_workers():
    return [n for n in ray_tpu.nodes() if n["state"] == "ALIVE" and not n["is_head"]]


def test_slice_fsm_to_running_and_drain():
    cluster = Cluster(head_resources={"CPU": 1})
    try:
        cluster.connect()
        api = FakeGceTpuApi(cluster.address, cluster._session_dir)
        provider = GceTpuNodeProvider(api, node_types=NODE_TYPES)
        im = InstanceManager(provider, NODE_TYPES)

        (iid,) = im.queue_instances("tpu_v5e_16", 1)
        im.reconcile(cluster_alive_count=1)
        assert im.instances()[0].status == InstanceStatus.REQUESTED

        # both hosts of the slice must register (gang create)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and len(_alive_workers()) < 2:
            time.sleep(0.5)
        workers = _alive_workers()
        assert len(workers) == 2, workers
        totals = [w["resources"]["total"] for w in workers]
        assert all(t.get("TPU") == 8 for t in totals)
        assert all(t.get("TPU-v5e-16") == 1 for t in totals)
        heads = [t for t in totals if t.get("TPU-v5e-16-head")]
        assert len(heads) == 1  # exactly one gang-scheduling head resource

        im.reconcile(cluster_alive_count=3)
        assert im.instances()[0].status == InstanceStatus.ALLOCATED
        im.reconcile(cluster_alive_count=3)
        assert im.instances()[0].status == InstanceStatus.RAY_RUNNING

        # drain: terminate takes the WHOLE slice down
        im.request_terminate(iid)
        im.reconcile(cluster_alive_count=3)
        assert im.instances(None)[0].status == InstanceStatus.TERMINATED
        assert provider.non_terminated_nodes() == []
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and _alive_workers():
            time.sleep(0.5)
        assert not _alive_workers(), "slice hosts survived termination"
    finally:
        cluster.shutdown()


def test_slice_gang_preemption():
    """One host dying marks the SLICE preempted; the ledger observes the
    provider-side disappearance and terminates the instance."""
    cluster = Cluster(head_resources={"CPU": 1})
    try:
        cluster.connect()
        api = FakeGceTpuApi(cluster.address, cluster._session_dir)
        provider = GceTpuNodeProvider(api, node_types=NODE_TYPES)
        im = InstanceManager(provider, NODE_TYPES)

        im.queue_instances("tpu_v5e_16", 1)
        im.reconcile(1)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and len(_alive_workers()) < 2:
            time.sleep(0.5)
        im.reconcile(3)
        im.reconcile(3)
        assert im.instances()[0].status == InstanceStatus.RAY_RUNNING

        slice_name = provider.non_terminated_nodes()[0]
        api.preempt(slice_name)
        time.sleep(1.0)
        # gang failure: any host down → slice no longer non-terminated
        assert provider.non_terminated_nodes() == []
        im.reconcile(1)
        assert im.instances(None)[0].status == InstanceStatus.TERMINATED
        api.delete_node(slice_name)  # reap the dead procs
    finally:
        cluster.shutdown()
