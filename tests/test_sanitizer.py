"""ConcSan unit tests: the runtime lockset witness, the owner-thread
discipline, sanctioned snapshots, the seeded interleaving fuzzer, and
the static↔dynamic lock-order cross-check.

The witness is process-global; every test runs inside the ``concsan``
fixture, which enables it, resets findings, and disables on the way out
so the rest of the tier-1 suite keeps its zero-overhead containers.
"""
import json
import textwrap
import threading

import pytest

from ray_tpu.util import lockwatch
from ray_tpu.util.guards import (
    OWNER_THREAD,
    GuardedDict,
    GuardedSet,
    guarded_by,
    snapshot,
)
from ray_tpu.tools.sanitizer import fuzzer, lockorder, runtime


@pytest.fixture
def concsan():
    runtime.enable()
    runtime.reset()
    yield runtime
    fuzzer.uninstall()
    runtime.reset()
    runtime.disable()


def _run_threads(*fns):
    threads = [
        threading.Thread(target=fn, name=f"t{i}") for i, fn in enumerate(fns)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _kinds():
    return [f["kind"] for f in runtime.report()["findings"]]


class _Owner:
    pass


# ---------------------------------------------------------------------------
# Eraser lockset witness


def test_checked_variant_selected_when_enabled(concsan):
    d = GuardedDict("_lock", owner=_Owner(), name="d")
    assert type(d).__name__ == "_CheckedGuardedDict"
    s = GuardedSet(OWNER_THREAD, owner=_Owner(), name="s")
    assert type(s).__name__ == "_CheckedGuardedSet"


def test_plain_variant_when_disabled():
    assert not runtime.enabled()
    d = GuardedDict("_lock", owner=_Owner(), name="d")
    assert type(d) is GuardedDict  # no checked accessors, C-speed ops
    # and it still round-trips through pickle as a plain dict
    import pickle

    assert pickle.loads(pickle.dumps(d)) == {}


def test_clean_locked_sharing_is_silent(concsan):
    owner = _Owner()
    lock = lockwatch.wrap(threading.Lock(), name="clean_lock")
    d = GuardedDict("_lock", owner=owner, name="d")

    def work():
        for i in range(50):
            with lock:
                d[i] = d.get(i, 0) + 1

    _run_threads(work, work)
    assert _kinds() == []


def test_unsynchronized_write_sharing_flags_empty_lockset(concsan):
    d = GuardedDict("_lock", owner=_Owner(), name="racy")

    def work():
        for i in range(50):
            d[i] = i  # no lock held anywhere: C(v) = ∅ once shared

    _run_threads(work, work)
    assert "empty_lockset" in _kinds()
    f = next(
        f for f in runtime.report()["findings"] if f["kind"] == "empty_lockset"
    )
    assert "racy" in f["state"] and f["held"] == []


def test_wrong_lock_sharing_flags_empty_lockset(concsan):
    """Two threads each hold *a* lock — but never the same one, so the
    candidate lockset intersects to ∅ (the classic wrong-lock race)."""
    d = GuardedDict("_lock", owner=_Owner(), name="wrong")
    l1 = lockwatch.wrap(threading.Lock(), name="l1")
    l2 = lockwatch.wrap(threading.Lock(), name="l2")

    def write(lock, key):
        def run():
            with lock:
                d[key] = key

        return run

    _run_threads(write(l1, 0))  # virgin -> exclusive
    _run_threads(write(l2, 1))  # shared_mod, C(v) = {l2}
    assert _kinds() == []  # consistent so far — each holds *a* lock
    _run_threads(write(l1, 2))  # C(v) = {l2} ∩ {l1} = ∅
    assert _kinds() == ["empty_lockset"]


def test_single_thread_use_never_flags(concsan):
    d = GuardedDict("_lock", owner=_Owner(), name="local")
    for i in range(100):
        d[i] = i  # exclusive state: no lockset refinement single-threaded
    assert _kinds() == []


# ---------------------------------------------------------------------------
# OWNER_THREAD discipline


def test_owner_thread_allows_one_transfer_then_flags(concsan):
    d = GuardedDict(OWNER_THREAD, owner=_Owner(), name="loop_state")
    d["ctor"] = 1  # constructor thread binds ownership

    def loop():
        for i in range(10):
            d[i] = i  # the one blessed handoff: ctor -> loop thread

    t = threading.Thread(target=loop, name="loop")
    t.start()
    t.join()
    assert _kinds() == []

    def intruder():
        d["x"] = 1  # third thread: the transfer budget is spent

    t = threading.Thread(target=intruder, name="pool-1")
    t.start()
    t.join()
    assert _kinds() == ["owner_thread"]
    f = runtime.report()["findings"][0]
    assert f["thread"] == "pool-1" and f["owner"] == "loop"


def test_owner_thread_snapshot_is_sanctioned(concsan):
    d = GuardedDict(OWNER_THREAD, owner=_Owner(), name="mirror")
    d["a"] = 1

    def loop():
        d["b"] = 2

    t = threading.Thread(target=loop, name="loop")
    t.start()
    t.join()

    out = {}

    def foreign_reader():
        out["copy"] = snapshot(d)  # the blessed cross-thread read

    t = threading.Thread(target=foreign_reader, name="telemetry")
    t.start()
    t.join()
    assert out["copy"] == {"a": 1, "b": 2} and isinstance(out["copy"], dict)
    assert _kinds() == []


def test_regression_log_tailer_drivers_peek(concsan):
    """Regression for the race ConcSan surfaced in the controller's log
    plane: ``_broadcast_logs`` (log-tailer thread) peeked at the
    loop-owned ``drivers`` set bare, spending the one ownership transfer
    and flagging the loop's own next access. The fix reads through
    ``snapshot()``. Replayed under the seeded schedule that surfaced it."""

    def scenario(peek):
        drivers = GuardedSet(OWNER_THREAD, owner=_Owner(), name="drivers")

        def loop():
            for i in range(5):
                drivers.add(i)

        def tailer():
            for _ in range(5):
                peek(drivers)

        t1 = threading.Thread(target=loop, name="loop")
        t1.start()
        t1.join()
        t2 = threading.Thread(target=tailer, name="log-tailer")
        t3 = threading.Thread(target=loop, name="loop-2")
        t2.start()
        t2.join()
        t3.start()
        t3.join()

    with fuzzer.fuzzing(seed=0):
        scenario(lambda s: bool(s))  # the pre-fix bare peek
    assert "owner_thread" in _kinds()
    assert runtime.report()["findings"][0]["fuzz_seed"] == 0

    runtime.reset()
    with fuzzer.fuzzing(seed=0):
        scenario(lambda s: bool(snapshot(s)))  # the fix
    assert _kinds() == []


# ---------------------------------------------------------------------------
# @guarded_by runtime contract


def test_guarded_by_method_entry_checked(concsan):
    class Store:
        def __init__(self):
            self._lock = lockwatch.wrap(threading.Lock(), name="store_lock")

        @guarded_by("_lock")
        def helper(self):
            return 1

    s = Store()
    with s._lock:
        s.helper()
    assert _kinds() == []
    s.helper()  # contract break: callers must hold _lock
    assert _kinds() == ["guard_method"]


# ---------------------------------------------------------------------------
# Fuzzer: determinism, sweep, replay


def test_fuzz_schedule_is_deterministic():
    a = fuzzer.FuzzSchedule(seed=7)
    b = fuzzer.FuzzSchedule(seed=7)
    seq_a = [a.decide("worker", "access", i) for i in range(200)]
    seq_b = [b.decide("worker", "access", i) for i in range(200)]
    assert seq_a == seq_b
    assert any(seq_a), "schedule never injects — period too sparse"
    c = fuzzer.FuzzSchedule(seed=8)
    assert seq_a != [c.decide("worker", "access", i) for i in range(200)]


def test_fuzzing_context_installs_and_uninstalls(concsan):
    assert fuzzer.active() is None
    with fuzzer.fuzzing(seed=3) as sched:
        assert fuzzer.active() is sched
        assert runtime.report()["fuzz_seed"] == 3
    assert fuzzer.active() is None
    assert runtime.report()["fuzz_seed"] is None


def test_sweep_finds_seed_and_replay_reproduces(concsan):
    seeds = range(3)

    def racy_workload():
        d = GuardedDict("_lock", owner=_Owner(), name="swept")

        def work():
            for i in range(30):
                d[i] = i

        _run_threads(work, work)

    seed = fuzzer.sweep(racy_workload, seeds, max_sleep_us=50)
    assert seed is not None
    runtime.reset()
    with fuzzer.fuzzing(seed, max_sleep_us=50):
        racy_workload()
    findings = runtime.report()["findings"]
    assert findings and findings[0]["fuzz_seed"] == seed


# ---------------------------------------------------------------------------
# Static ↔ dynamic lock-order cross-check


_LOCKORDER_SRC = """
    import threading

    class C:
        def __init__(self):
            self._a_lock = threading.Lock()
            self._b_lock = threading.Lock()
            self._c_lock = threading.Lock()

        def nested(self):
            with self._a_lock:
                with self._b_lock:
                    pass
"""


def _write_project(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent(_LOCKORDER_SRC))
    return str(tmp_path)


def _site_of(graph, root, canon):
    for (rel, line), name in graph.creation_sites.items():
        if name == canon:
            import os

            return {"file": os.path.join(root, rel), "line": line}
    raise AssertionError(f"no creation site for {canon}")


def test_build_static_edges_and_sites(tmp_path):
    root = _write_project(tmp_path)
    g = lockorder.build_static(root, paths=["."])
    assert ("mod.C._a_lock", "mod.C._b_lock") in g.edges
    assert {"mod.C._a_lock", "mod.C._b_lock", "mod.C._c_lock"} <= set(
        g.creation_sites.values()
    )


def test_cross_check_classification(tmp_path):
    root = _write_project(tmp_path)
    g = lockorder.build_static(root, paths=["."])
    a = _site_of(g, root, "mod.C._a_lock")
    b = _site_of(g, root, "mod.C._b_lock")
    c = _site_of(g, root, "mod.C._c_lock")

    def edge(src, dst):
        return {"src_site": src, "dst_site": dst, "observed_at": "mod.py:1"}

    dynamic = [
        edge(a, b),  # lexically explained
        edge(b, c),  # order the AST never saw
        edge({"file": "/elsewhere/x.py", "line": 1}, a),  # test-created lock
    ]
    out = lockorder.cross_check(root, dynamic, static=g, paths=["."])
    assert [e["src"] for e in out["matched"]] == ["mod.C._a_lock"]
    assert [(e["src"], e["dst"]) for e in out["dynamic_only"]] == [
        ("mod.C._b_lock", "mod.C._c_lock")
    ]
    assert out["external_edges"] == 1

    # an allowlist entry with a justification reclassifies the edge
    (tmp_path / lockorder.ALLOWLIST_FILE).write_text(
        json.dumps(
            {
                "edges": [
                    {
                        "src": "mod.C._b_lock",
                        "dst": "mod.C._c_lock",
                        "justification": "b->c reached via data-driven dispatch",
                    }
                ]
            }
        )
    )
    out = lockorder.cross_check(root, dynamic, static=g, paths=["."])
    assert out["dynamic_only"] == []
    assert out["allowlisted"][0]["justification"].startswith("b->c")


def test_guarded_by_counts_as_holding_its_guard(tmp_path):
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import threading
            from ray_tpu.util.guards import guarded_by

            class C:
                def __init__(self):
                    self._outer_lock = threading.Lock()
                    self._inner_lock = threading.Lock()

                @guarded_by("_outer_lock")
                def helper(self):
                    with self._inner_lock:
                        pass
            """
        )
    )
    g = lockorder.build_static(str(tmp_path), paths=["."])
    assert ("mod.C._outer_lock", "mod.C._inner_lock") in g.derived


def test_one_hop_call_through_derives_edge(tmp_path):
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import threading

            class C:
                def __init__(self):
                    self._outer_lock = threading.Lock()
                    self._inner_lock = threading.Lock()

                def outer(self):
                    with self._outer_lock:
                        self.inner()

                def inner(self):
                    with self._inner_lock:
                        pass
            """
        )
    )
    g = lockorder.build_static(str(tmp_path), paths=["."])
    assert ("mod.C._outer_lock", "mod.C._inner_lock") in g.derived
    assert ("mod.C._outer_lock", "mod.C._inner_lock") not in g.edges


# ---------------------------------------------------------------------------
# Process reports


def test_report_dump_and_load(tmp_path, concsan):
    d = GuardedDict("_lock", owner=_Owner(), name="dumped")

    def work():
        for i in range(30):
            d[i] = i

    _run_threads(work, work)
    assert _kinds()  # the planted race above produced at least one
    runtime._dump_report(str(tmp_path))
    reports = runtime.load_reports(str(tmp_path))
    assert len(reports) == 1
    r = reports[0]
    assert r["enabled"] and r["findings"]
    assert isinstance(r["lock_graph"], list)
    # unreadable files are skipped, not fatal
    (tmp_path / "concsan-9999.json").write_text("{not json")
    assert len(runtime.load_reports(str(tmp_path))) == 1
