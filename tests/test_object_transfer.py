"""Network object data plane: node↔node chunked transfer.

Reference test model: python/ray/tests/test_object_manager.py (push/pull
across nodes). Cross-node shm mapping is DISABLED by default
(``cross_node_shm=False``), so these tests prove the network path moves
the bytes — the topology a real multi-host pod has.
"""
import numpy as np
import pytest

import ray_tpu
from ray_tpu.core.cluster_utils import Cluster


@pytest.fixture
def two_node_cluster():
    cluster = Cluster({"CPU": 2})
    cluster.add_node(num_cpus=2, resources={"remote_node": 10})
    cluster.connect()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


MB = 1024 * 1024


def test_cross_node_get_over_network(two_node_cluster):
    """An object produced on node B is get-able from the driver (head)
    via chunked network pull — no cross-node shm open."""

    @ray_tpu.remote(resources={"remote_node": 1})
    def produce():
        return np.arange(4 * MB, dtype=np.uint8).reshape(4, MB)

    arr = ray_tpu.get(produce.remote(), timeout=120)
    assert arr.shape == (4, MB)
    assert arr[2, 5] == np.uint8(5)


def test_driver_object_read_on_remote_node(two_node_cluster):
    data = np.full(3 * MB, 7, dtype=np.uint8)
    ref = ray_tpu.put(data)

    @ray_tpu.remote(resources={"remote_node": 1})
    def consume(x):
        return int(x.sum())

    assert ray_tpu.get(consume.remote(ref), timeout=120) == 7 * 3 * MB


def test_concurrent_pulls_coalesce(two_node_cluster):
    """Two readers on the remote node pulling the same object at once."""
    data = np.ones(4 * MB, dtype=np.uint8)
    ref = ray_tpu.put(data)

    @ray_tpu.remote(resources={"remote_node": 0.5})
    def consume(x):
        return int(x[0]) + int(x[-1])

    out = ray_tpu.get([consume.remote(ref), consume.remote(ref)], timeout=120)
    assert out == [2, 2]


def test_round_trip_both_directions(two_node_cluster):
    """head→node and node→head transfers of the same bytes agree."""

    @ray_tpu.remote(resources={"remote_node": 1})
    def bounce(x):
        return x * 2

    data = np.arange(2 * MB, dtype=np.int32)
    out = ray_tpu.get(bounce.remote(ray_tpu.put(data)), timeout=120)
    np.testing.assert_array_equal(out, data * 2)


def test_cross_node_shm_legacy_mode():
    """cross_node_shm=True keeps the single-host mmap shortcut working."""
    cluster = Cluster({"CPU": 2}, system_config={"cross_node_shm": True})
    cluster.add_node(num_cpus=2, resources={"remote_node": 10})
    cluster.connect()
    try:

        @ray_tpu.remote(resources={"remote_node": 1})
        def produce():
            return np.zeros(2 * MB, dtype=np.uint8)

        arr = ray_tpu.get(produce.remote(), timeout=120)
        assert arr.nbytes == 2 * MB
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
