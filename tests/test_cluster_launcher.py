"""Cluster launcher: up / exec / down over the NodeProvider layer.

Reference: python/ray/scripts/scripts.py:2548-2579 (ray up/down/attach/
exec) + autoscaler/_private/commands.py, exercised on the fake provider
the way the reference tests the launcher on FakeMultiNodeProvider.
"""
import json
import os
import subprocess
import sys
import time

import pytest


@pytest.mark.slow
def test_up_exec_down_fake_provider(tmp_path, monkeypatch):
    # isolate cluster-state files from the user's home
    monkeypatch.setenv("HOME", str(tmp_path))
    cfg = tmp_path / "cluster.yaml"
    cfg.write_text(
        """
cluster_name: launcher_test
provider:
  type: fake
head_resources: {CPU: 2}
idle_timeout_s: 300
available_node_types:
  worker:
    resources: {CPU: 2, marker: 1}
    min_workers: 2
    max_workers: 4
"""
    )
    from ray_tpu.autoscaler.commands import (
        create_or_update_cluster,
        exec_on_cluster,
        read_cluster_state,
        teardown_cluster,
    )

    state = create_or_update_cluster(str(cfg))
    try:
        assert state["cluster_name"] == "launcher_test"
        assert read_cluster_state("launcher_test")["address"] == state["address"]
        # idempotent re-up returns the live cluster
        assert create_or_update_cluster(str(cfg))["address"] == state["address"]

        # the monitor must bring up min_workers=2 agents: head + 2 ALIVE
        check = (
            "import ray_tpu, json, time\n"
            "ray_tpu.init(address='auto')\n"
            "deadline = time.time() + 90\n"
            "while time.time() < deadline:\n"
            "    alive = [n for n in ray_tpu.nodes() if n['state'] == 'ALIVE']\n"
            "    if len(alive) >= 3: break\n"
            "    time.sleep(0.5)\n"
            "print(json.dumps({'alive': len(alive)}))\n"
            "assert len(alive) >= 3, alive\n"
            # run a task on a provisioned worker (its marker resource)
            "@ray_tpu.remote(resources={'marker': 0.1})\n"
            "def where():\n"
            "    from ray_tpu import runtime_context\n"
            "    return runtime_context.get_runtime_context().get_node_id()\n"
            "print(json.dumps({'ran_on': ray_tpu.get(where.remote(), timeout=60)}))\n"
            "ray_tpu.shutdown()\n"
        )
        # exec: the command runs against the launched head via
        # RAY_TPU_ADDRESS (ray_tpu.init(address='auto'))
        r = exec_on_cluster(
            "launcher_test", [sys.executable, "-c", check], capture=True
        )
        assert r.returncode == 0, r.stderr
        lines = [json.loads(l) for l in r.stdout.strip().splitlines() if l.startswith("{")]
        assert lines[0]["alive"] >= 3, r.stdout
        assert lines[1]["ran_on"], r.stdout
    finally:
        state = teardown_cluster("launcher_test")
    # everything must be gone: head, monitor, provisioned agents
    deadline = time.time() + 20
    while time.time() < deadline:
        out = subprocess.run(
            ["ps", "-eo", "pid,cmd"], capture_output=True, text=True
        ).stdout
        leftovers = [
            l for l in out.splitlines()
            if state["session_dir"] in l and "grep" not in l
        ]
        if not leftovers:
            break
        time.sleep(0.5)
    assert not leftovers, leftovers
    assert not os.path.exists(
        os.path.join(str(tmp_path), ".ray_tpu", "clusters", "launcher_test.json")
    )
