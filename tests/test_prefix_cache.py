"""Serve-path engine perf suite: prefix-aware KV reuse, chunked prefill,
host/device overlap, bucket warmup, and dirty-slot shipping.

Correctness contract for every feature: temp-0 outputs must be
IDENTICAL to the plain engine (same math, different scheduling /
memory reuse), plus allocator/refcount invariants that guard against
cross-request block aliasing.
"""
import jax
import jax.numpy as jnp
import pytest

from ray_tpu.models.paged import PagedConfig, TRASH_BLOCK
from ray_tpu.models.transformer import TransformerConfig, init_params
from ray_tpu.serve.llm_engine import LLMEngine, _PrefixCache


@pytest.fixture(autouse=True)
def _highest_precision():
    """Token-for-token assertions across differently-shaped computations
    of the same math (full vs chunked prefill, cached vs recomputed KV);
    fp32 matmul precision keeps rounding from flipping an argmax."""
    prev = jax.config.jax_default_matmul_precision
    jax.config.update("jax_default_matmul_precision", "highest")
    yield
    jax.config.update("jax_default_matmul_precision", prev)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = TransformerConfig.tiny(dtype=jnp.float32, remat=False)
    params = init_params(jax.random.PRNGKey(7), cfg)
    params = jax.tree.map(lambda x: jax.device_put(x), params)
    return cfg, params


def _engine(cfg, params, **kw):
    pcfg_kw = dict(block_size=8, num_blocks=33, max_batch=4, max_blocks_per_seq=8)
    for k in list(kw):
        if k in pcfg_kw:
            pcfg_kw[k] = kw.pop(k)
    return LLMEngine(params, cfg, PagedConfig(**pcfg_kw), **kw)


SHARED = [7, 3, 9, 1, 4, 6, 2, 8, 11, 12, 13, 14, 15, 16, 17, 18, 21, 22, 23, 24]


def _cache_invariants(eng):
    """No block may be simultaneously free, cached, and/or slot-owned."""
    pc = eng.prefix_cache
    assert len(eng.alloc.free) == len(set(eng.alloc.free)), "double-freed block"
    free = set(eng.alloc.free)
    cached = set(pc.meta)
    in_use = {b for bl in eng.slot_blocks for b in bl}
    assert not free & cached, "block both free and cache-resident"
    assert TRASH_BLOCK not in free and TRASH_BLOCK not in cached
    # Every cached-but-referenced block must be mapped by some slot, and
    # every refcount must equal the number of slots mapping it.
    for bid, (_key, _parent, refs) in pc.meta.items():
        mapped = sum(bl.count(bid) for bl in eng.slot_blocks)
        assert refs == mapped, f"block {bid}: refs {refs} != mapped {mapped}"
        if refs == 0:
            assert bid in pc.lru
            assert bid not in in_use
    # Full accounting: free + cached(ref0) + slot-owned == usable pool.
    owned_or_resident = len(free) + len(pc.lru) + len(in_use - cached)
    # slot-owned cached blocks are counted via in_use∩cached == refs>0 set
    owned_or_resident += len(in_use & cached)
    assert owned_or_resident == eng.pcfg.usable_blocks


def test_prefix_cache_temp0_outputs_identical(tiny_model):
    """Requests sharing a prompt prefix must produce byte-identical
    greedy outputs with the cache on vs off, while >= 30% of prompt
    tokens are served from cache."""
    cfg, params = tiny_model
    prompts = [SHARED + [30 + i, 40 + i, 50 + i] for i in range(4)]
    base = _engine(cfg, params)
    expect = [base.generate_batch([p], 8)[0] for p in prompts]
    eng = _engine(cfg, params, enable_prefix_cache=True)
    outs = [eng.generate_batch([p], 8)[0] for p in prompts]
    assert outs == expect
    s = eng.stats
    assert s["prefix_lookup_tokens"] == sum(len(p) for p in prompts)
    # 3 warm requests x 2 full shared blocks (16 tokens) each.
    assert s["prefix_hit_tokens"] == 48
    assert s["prefix_hit_tokens"] / s["prefix_lookup_tokens"] >= 0.30
    # Cached prompt tokens were NOT prefilled again.
    assert s["prompt_tokens"] == s["prefix_lookup_tokens"] - s["prefix_hit_tokens"]
    _cache_invariants(eng)


def test_prefix_cache_refcounts_and_concurrent_sharing(tiny_model):
    """Concurrent requests sharing cached blocks pin them (refcount = #
    of mapping slots); finishing releases them into the LRU, never the
    free list, and the outputs still match the plain engine."""
    cfg, params = tiny_model
    prompts = [SHARED + [60 + i] for i in range(3)]
    base = _engine(cfg, params)
    expect = [base.generate_batch([p], 6)[0] for p in prompts]
    eng = _engine(cfg, params, enable_prefix_cache=True)
    # Warm the cache, then run the rest concurrently so they share blocks.
    first = eng.generate_batch([prompts[0]], 6)
    rest = eng.generate_batch(prompts[1:], 6)
    assert [first[0]] + rest == expect
    pc = eng.prefix_cache
    assert pc.resident_blocks == 2  # the two full shared blocks
    assert pc.evictable_blocks == 2  # all refs dropped at finish
    for bid, (_k, _p, refs) in pc.meta.items():
        assert refs == 0
    _cache_invariants(eng)


def test_prefix_cache_eviction_no_stale_aliasing(tiny_model):
    """Fill the pool with distinct prompts until cached blocks are
    evicted and re-allocated, then re-submit the first prompt: it must
    recompute (no stale hit via a reused block id) and match exactly."""
    cfg, params = tiny_model
    # Tiny pool: 12 usable blocks, so distinct prompts evict each other.
    kw = dict(num_blocks=13, max_batch=2, max_blocks_per_seq=6)
    base = _engine(cfg, params, **kw)
    eng = _engine(cfg, params, enable_prefix_cache=True, **kw)
    first = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17]
    others = [[i + 20] * 17 for i in range(6)]
    expect_first = base.generate_batch([first], 6)
    expect_others = [base.generate_batch([p], 6)[0] for p in others]
    assert eng.generate_batch([first], 6) == expect_first
    for p, exp in zip(others, expect_others):
        assert eng.generate_batch([p], 6)[0] == exp
        _cache_invariants(eng)
    assert eng.stats["prefix_evictions"] > 0
    # Re-run the first prompt after its blocks were evicted/reused.
    assert eng.generate_batch([first], 6) == expect_first
    _cache_invariants(eng)


def test_prefix_cache_preempt_resume_hits(tiny_model):
    """Preempted requests resume via re-prefill; with the cache on, the
    resume maps the already-resident prompt blocks instead of paying the
    full recompute — and still finishes with identical greedy output."""
    cfg, params = tiny_model
    kw = dict(num_blocks=13, max_batch=4, max_blocks_per_seq=6)
    prompts = [[i + 1, i + 2, i + 3, i + 4] * 2 for i in range(4)]
    calm = _engine(cfg, params)
    expect = calm.generate_batch(prompts, 28)
    eng = _engine(cfg, params, enable_prefix_cache=True, **kw)
    outs = eng.generate_batch(prompts, 28)
    assert outs == expect
    assert eng.stats["preemptions"] > 0
    assert eng.stats["prefix_hit_tokens"] > 0  # resume reused resident KV
    _cache_invariants(eng)


def test_chunked_prefill_matches_and_interleaves(tiny_model):
    """A long prompt split into chunks must decode identically, and a
    short stream admitted alongside keeps producing tokens between the
    long prompt's chunks (no head-of-line freeze)."""
    cfg, params = tiny_model
    long_p = list(range(1, 49))  # 48 tokens -> 6 chunks of 8
    short_p = [9, 8, 7]
    base = _engine(cfg, params)
    expect_long = base.generate_batch([long_p], 8)[0]
    expect_short = _engine(cfg, params).generate_batch([short_p], 12)[0]
    eng = _engine(cfg, params, prefill_chunk=8)
    short_req = eng.add_request(short_p, 12)
    eng.step()  # admit + prefill the short request first
    long_req = eng.add_request(long_p, 8)
    chunks_before_done = None
    while eng.active_count() or eng.waiting:
        eng.step()
        if chunks_before_done is None and short_req.out.qsize() > 2:
            # Short stream progressed while the long prefill is running.
            chunks_before_done = eng.stats["prefill_chunks"]
    assert list(long_req.tokens(timeout=60)) == expect_long
    assert list(short_req.tokens(timeout=60)) == expect_short
    assert eng.stats["prefill_chunks"] >= 6
    assert chunks_before_done is not None and chunks_before_done < 6


def test_chunked_prefill_with_cache_and_overlap(tiny_model):
    """The full perf suite composed: chunked prefill + prefix cache +
    overlap, greedy outputs identical to the plain engine."""
    cfg, params = tiny_model
    prompts = [SHARED + SHARED[:12] + [70 + i] for i in range(4)]  # 33 tokens
    base = _engine(cfg, params)
    expect = [base.generate_batch([p], 6)[0] for p in prompts]
    eng = _engine(
        cfg, params, enable_prefix_cache=True, prefill_chunk=16,
        overlap=True, decode_window=2,
    )
    outs = [eng.generate_batch([p], 6)[0] for p in prompts]
    assert outs == expect
    assert eng.stats["prefill_chunks"] > 0
    assert eng.stats["prefix_hit_tokens"] > 0
    _cache_invariants(eng)


def test_warmup_buckets(tiny_model):
    """Opt-in warmup compiles every prefill bucket at build time and
    records the spent wall time; live requests then behave identically."""
    cfg, params = tiny_model
    eng = _engine(cfg, params, warmup_buckets=True, enable_prefix_cache=True)
    # tiny: buckets 8..64 (4 prefill + 4 suffix-chunk) + decode = 9.
    assert eng.stats["warmup_compiles"] == 9
    assert eng.stats["warmup_s"] >= 0
    assert eng.alloc.available == eng.pcfg.usable_blocks  # warmup hit trash only
    base = _engine(cfg, params)
    prompts = [[5, 9, 2, 11, 3], [17, 1, 8]]
    assert eng.generate_batch(prompts, 8) == base.generate_batch(prompts, 8)


def test_dirty_slot_shipping_skips_stable_arrays(tiny_model):
    """Steady-state decode must not re-upload tables/lens/temps/cur every
    window: only admission/retirement/paging dirties them."""
    cfg, params = tiny_model
    eng = _engine(cfg, params, decode_window=1)
    eng.generate_batch([[5, 9, 2]], max_new_tokens=24)
    s = eng.stats
    assert s["h2d_skips"] > 0
    # 4 arrays x steps would be the wholesale-upload cost; dirty tracking
    # must beat it by a wide margin (tables only change on block faults).
    assert s["h2d_ships"] < 4 * s["steps"] / 2


def test_overlap_requires_wider_margin(tiny_model):
    """Overlap doubles the decode-window overshoot margin: a request that
    fits the classic margin but not 2*window-1 must be rejected up front
    (its speculated window could write past its block table)."""
    cfg, params = tiny_model
    eng = _engine(cfg, params, decode_window=4, overlap=True)  # max_seq 64
    req = eng.add_request([1] * 30, max_new_tokens=28)  # 30+28+7 = 65 > 64
    with pytest.raises(RuntimeError, match="exceeds capacity"):
        list(req.tokens(timeout=5))
    ok = eng.add_request([1] * 30, max_new_tokens=27)  # 64 — fits
    eng_out = []
    while eng.active_count() or eng.waiting:
        eng.step()
    eng_out = list(ok.tokens(timeout=5))
    assert len(eng_out) == 27


def test_eviction_spares_pinned_child_under_unpinned_chain(tiny_model):
    """A request that registers a novel tail under a chain ANOTHER
    request published first holds no references on that chain (its own
    table maps private duplicates of the parents) — so the chain can hit
    refcount 0 and be evicted while the child is pinned by a live slot.
    The eviction cascade must unregister such a child but NEVER free it:
    pre-fix this freed a block still mapped by a decoding request (KV
    corruption) and then double-freed it at slot release."""
    cfg, params = tiny_model
    eng = _engine(cfg, params, enable_prefix_cache=True, prefill_chunk=16,
                  num_blocks=15, max_batch=4)
    shared = list(range(1, 17))  # 2 full shared blocks
    a_prompt = shared + list(range(30, 54))  # 40 tokens, chunked (3 chunks)
    b_prompt = shared  # 16 tokens, single-shot: registers the chain FIRST
    c_prompt = [200 + i for i in range(40)]  # distinct: forces eviction
    calm = _engine(cfg, params)
    a_ref = calm.generate_batch([a_prompt], 24)[0]
    b_ref = calm.generate_batch([b_prompt], 2)[0]
    c_ref = calm.generate_batch([c_prompt], 4)[0]
    # A (chunked, registration deferred) + B (instant registration) race:
    # B publishes the shared chain; A's tail registers under B's blocks.
    a = eng.add_request(a_prompt, 24)
    b = eng.add_request(b_prompt, 2)
    while eng.slots[1] is not None or eng.waiting:  # B admitted+finished
        eng.step()
    assert list(b.tokens(timeout=60)) == b_ref
    # B's chain is now refcount-0/evictable while A still decodes with
    # its tail blocks registered (pinned) beneath it. C's admission must
    # evict B's chain — and must not touch A's pinned blocks.
    c = eng.add_request(c_prompt, 4)
    while eng.active_count() or eng.waiting:
        eng.step()
    assert eng.stats["prefix_evictions"] >= 2  # B's two chain blocks
    assert list(a.tokens(timeout=60)) == a_ref  # A's KV never corrupted
    assert list(c.tokens(timeout=60)) == c_ref
    _cache_invariants(eng)


def test_prefix_cache_unit_eviction_cascades():
    """Unit: evicting a parent must evict its cached descendants, so a
    reused parent id can never falsely re-link a stale child chain."""
    pc = _PrefixCache()
    a = pc.register(_PrefixCache.ROOT, (1, 2), 10)
    b = pc.register(a, (3, 4), 11)
    c = pc.register(b, (5, 6), 12)
    assert (a, b, c) == (10, 11, 12)
    for bid in (10, 11, 12):
        pc.release(bid)
    assert pc.evictable_blocks == 3
    freed = pc.evict_lru()  # coldest = 10, cascades to 11, 12
    assert set(freed) == {10, 11, 12}
    assert pc.resident_blocks == 0 and not pc.table
    # Re-register under the same ids with different tokens: no stale hits.
    pc.register(_PrefixCache.ROOT, (9, 9), 10)
    assert pc.match([1, 2, 3, 4], 2, 2) == []
    assert pc.match([9, 9, 3, 4], 2, 2) == [10]


@pytest.mark.slow
def test_engine_perf_suite_stress(tiny_model):
    """Long-running mixed workload (cache + chunks + overlap + windows +
    preemption pressure): invariants hold and every request completes
    with the right token count."""
    cfg, params = tiny_model
    eng = _engine(
        cfg, params, enable_prefix_cache=True, prefill_chunk=16,
        overlap=True, decode_window=4, num_blocks=25,
    )
    reqs = []
    for r in range(6):
        for i in range(6):
            n = 4 + (i * 7 + r) % 9
            reqs.append(eng.add_request(SHARED + [r, i], max_new_tokens=n))
        while eng.active_count() or eng.waiting:
            eng.step()
    for q in reqs:
        toks = list(q.tokens(timeout=60))
        assert len(toks) == q.max_new_tokens
    _cache_invariants(eng)
