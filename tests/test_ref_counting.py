"""Distributed ref counting / object GC (reference test model:
python/ray/tests/test_reference_counting.py + _2: out-of-scope refs are
freed; pinned/borrowed/contained refs are not)."""
import gc
import time

import numpy as np
import pytest

import ray_tpu
from conftest import shared_cluster_fixtures
from ray_tpu.core import api

# One cluster for the whole file (suite-time headroom), on a fast GC
# cadence: flush 50ms + sweep 150ms (the 2x safety floor) means one full
# flush+sweep cycle is ~0.2s, so the "several cycles" sleeps below stay
# several cycles at a fraction of the default 0.2s+1s wall time.
ray_start_regular, _shared_cluster_guard = shared_cluster_fixtures(
    num_cpus=4,
    resources={"TPU": 4},
    _system_config={"ref_flush_interval_ms": 50, "gc_sweep_interval_ms": 150},
)

BIG = 300_000  # > inline limit → shm object


def _object_listed(hex_id: str) -> bool:
    objs = api._require_worker()._call("list_objects")
    return any(o["object_id"] == hex_id for o in objs)


def _wait_freed(hex_id: str, timeout: float = 12.0) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if not _object_listed(hex_id):
            return True
        time.sleep(0.05)
    return False


def test_dropped_put_is_freed(ray_start_regular):
    ref = ray_tpu.put(np.zeros(BIG, np.uint8))
    hex_id = ref.hex()
    assert ray_tpu.get(ref).shape == (BIG,)
    assert _object_listed(hex_id)
    del ref
    gc.collect()
    assert _wait_freed(hex_id), "unreferenced object was never GCed"


def test_dropped_inline_put_is_freed(ray_start_regular):
    ref = ray_tpu.put(b"small")
    hex_id = ref.hex()
    assert ray_tpu.get(ref) == b"small"
    del ref
    gc.collect()
    assert _wait_freed(hex_id)


def test_held_ref_is_not_freed(ray_start_regular):
    ref = ray_tpu.put(np.ones(BIG, np.uint8))
    time.sleep(0.8)  # several flush+sweep cycles (~0.2s each here)
    assert ray_tpu.get(ref)[0] == 1


def test_task_return_freed_after_drop(ray_start_regular):
    @ray_tpu.remote
    def f():
        return np.zeros(BIG, np.uint8)

    ref = f.remote()
    hex_id = ref.hex()
    assert ray_tpu.get(ref).shape == (BIG,)
    del ref
    gc.collect()
    assert _wait_freed(hex_id)


def test_borrowed_ref_keeps_object_alive(ray_start_regular):
    """A worker holding a deserialized copy of the ref (borrower) must
    keep the object alive after the driver drops its own ref."""

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.ref = None

        def keep(self, boxed):
            self.ref = boxed[0]  # nested → arrives as an ObjectRef
            return True

        def read(self):
            return int(ray_tpu.get(self.ref)[0])

    h = Holder.remote()
    ref = ray_tpu.put(np.full(BIG, 9, np.uint8))
    hex_id = ref.hex()
    assert ray_tpu.get(h.keep.remote([ref])) is True
    del ref
    gc.collect()
    time.sleep(0.8)  # flushes + sweeps: borrower must protect it
    assert _object_listed(hex_id), "borrowed object was wrongly freed"
    assert ray_tpu.get(h.read.remote()) == 9
    ray_tpu.kill(h)


def test_contained_ref_pinned_by_container(ray_start_regular):
    @ray_tpu.remote
    def make():
        inner = ray_tpu.put(np.full(BIG, 7, np.uint8))
        return {"inner": inner}

    out_ref = make.remote()
    out = ray_tpu.get(out_ref)
    time.sleep(0.6)  # the producing worker's local ref is long gone
    assert ray_tpu.get(out["inner"])[0] == 7
    # dropping the container AND the extracted inner ref frees the inner
    inner_hex = out["inner"].hex()
    del out, out_ref
    gc.collect()
    assert _wait_freed(inner_hex)


def test_pending_task_args_pinned(ray_start_regular):
    @ray_tpu.remote
    def slow(x, lst):
        time.sleep(0.8)
        inner = ray_tpu.get(lst[0])
        return float(x[0] + inner[0])

    top = ray_tpu.put(np.full(BIG, 3, np.uint8))
    nested = ray_tpu.put(np.full(BIG, 4, np.uint8))
    fut = slow.remote(top, [nested])
    del top, nested
    gc.collect()
    time.sleep(0.3)  # driver's drops flush while the task still runs
    assert ray_tpu.get(fut) == 7.0


def test_explicit_free_still_works(ray_start_regular):
    from ray_tpu.core.api import free

    ref = ray_tpu.put(np.zeros(BIG, np.uint8))
    hex_id = ref.hex()
    free([ref])
    assert not _object_listed(hex_id)


def test_auto_gc_can_be_disabled():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()  # needs its own (auto_gc off) cluster
    cfg = {
        "object_auto_gc": False,
        "ref_flush_interval_ms": 50,
        "gc_sweep_interval_ms": 150,
    }
    ray_tpu.init(num_cpus=1, _system_config=cfg)
    try:
        ref = ray_tpu.put(np.zeros(BIG, np.uint8))
        hex_id = ref.hex()
        del ref
        gc.collect()
        time.sleep(0.8)  # several flush+sweep cycles on the fast cadence
        assert _object_listed(hex_id), "object freed despite auto_gc off"
    finally:
        ray_tpu.shutdown()


def test_actor_creation_args_pinned(ray_start_regular):
    """Creation args must survive GC while the actor can (re)start —
    restarts re-run __init__ with the same args."""

    @ray_tpu.remote
    class A:
        def __init__(self, arr, boxed):
            self.v = int(arr[0]) + int(ray_tpu.get(boxed[0])[0])

        def read(self):
            return self.v

    top = ray_tpu.put(np.full(BIG, 2, np.uint8))
    nested = ray_tpu.put(np.full(BIG, 3, np.uint8))
    a = A.options(max_restarts=1).remote(top, [nested])
    del top, nested
    gc.collect()
    time.sleep(0.8)  # flush + sweep cycles while creation may be pending
    assert ray_tpu.get(a.read.remote()) == 5
    ray_tpu.kill(a)


def test_get_freed_object_fails_fast(ray_start_regular):
    from ray_tpu.core.api import free
    from ray_tpu.exceptions import ObjectLostError
    import copy

    ref = ray_tpu.put(np.zeros(BIG, np.uint8))
    clone = copy.copy(ref)  # second local ref to the same oid
    free([ref])
    with pytest.raises(ObjectLostError):
        ray_tpu.get(clone, timeout=10)


def test_task_on_freed_dep_fails_fast(ray_start_regular):
    from ray_tpu.core.api import free
    from ray_tpu.exceptions import ObjectLostError
    import copy

    ref = ray_tpu.put(np.zeros(BIG, np.uint8))
    clone = copy.copy(ref)
    free([ref])

    @ray_tpu.remote
    def consume(x):
        return x.shape

    with pytest.raises(ObjectLostError):
        ray_tpu.get(consume.remote(clone), timeout=15)
