"""Actor creation claims prestarted direct-pool workers.

Reference: src/ray/raylet/worker_pool.h:363-374 — PopWorker makes no
task/actor distinction; a warm pool must serve actor creation too
(VERDICT r4 weak #4: cold-spawning every actor while pooled workers sit
idle).
"""
import os
import time

import pytest

import ray_tpu


@pytest.fixture
def cluster_no_prestart():
    # prestart off → no controller-side IDLE workers; the only warm
    # workers are the direct-lease pool, so a pooled-pid match proves the
    # claim path specifically.
    ray_tpu.init(num_cpus=4, resources={"TPU": 0},
                 _system_config={"prestart_workers": False})
    yield ray_tpu
    ray_tpu.shutdown()


def test_actor_creation_claims_pooled_worker(cluster_no_prestart):
    @ray_tpu.remote(num_cpus=0.001)
    def task_pid():
        return os.getpid()

    # Populate the direct pool: these run via the lease path, spawning
    # direct workers that return to the pool afterwards.
    pooled = set(ray_tpu.get([task_pid.remote() for _ in range(4)], timeout=60))
    assert pooled

    # Lease release is an async notify fired when the caller's queue
    # drains — wait for the workers to actually land back in the pool
    # (state DIRECT), or the claim below races the release and
    # legitimately cold-spawns. The pool's pids are the claimable set:
    # the lease ramp may have spawned MORE workers than distinct task
    # pids (a spawn that attached after the queue drained never ran a
    # task), and any of them is a valid claim.
    from ray_tpu.util import state as state_api

    deadline = time.time() + 10
    pool_pids: set = set()
    while time.time() < deadline:
        workers = state_api.list_workers()
        pool_pids = {w["pid"] for w in workers if w["state"] == "DIRECT"}
        if pool_pids and not any(w["state"] == "LEASED" for w in workers):
            break
        time.sleep(0.05)
    assert pool_pids >= pooled, (pool_pids, pooled)

    @ray_tpu.remote(num_cpus=0.001)
    class A:
        def pid(self):
            return os.getpid()

    a = A.remote()
    apid = ray_tpu.get(a.pid.remote(), timeout=60)
    assert apid in pool_pids, (
        f"actor cold-spawned (pid {apid}) while pooled workers {pool_pids} sat idle"
    )


def test_claimed_actor_worker_leaves_the_pool(cluster_no_prestart):
    """After an actor claims a pooled worker, tasks must NOT land on the
    actor's worker process (it left the free pool)."""

    @ray_tpu.remote(num_cpus=0.001)
    def task_pid():
        return os.getpid()

    ray_tpu.get([task_pid.remote() for _ in range(2)], timeout=60)

    @ray_tpu.remote(num_cpus=0.001)
    class A:
        def pid(self):
            return os.getpid()

    a = A.remote()
    apid = ray_tpu.get(a.pid.remote(), timeout=60)
    for _ in range(6):
        assert ray_tpu.get(task_pid.remote(), timeout=60) != apid
    # The actor is still alive and serving.
    assert ray_tpu.get(a.pid.remote(), timeout=30) == apid


def test_warm_pool_actor_burst_is_fast(cluster_no_prestart):
    """A burst of actors onto a warm pool must not pay per-actor process
    spawns (the claim path is control-plane-only)."""

    @ray_tpu.remote(num_cpus=0.001)
    def nap():
        time.sleep(1.0)
        return os.getpid()

    # Force the pool wide: concurrent naps hold one worker each (lease
    # ramp-up caps concurrency near the CPU count, so take what we get).
    warm = set(ray_tpu.get([nap.remote() for _ in range(8)], timeout=120))
    assert len(warm) >= 2

    @ray_tpu.remote(num_cpus=0.001)
    class A:
        def pid(self):
            return os.getpid()

    n = len(warm)
    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    pids = ray_tpu.get([a.pid.remote() for a in actors], timeout=120)
    dt = time.perf_counter() - t0
    # The pool may hand out pristine REPLACEMENT workers (spawned when
    # the naps popped it) rather than the exact nap pids — what matters
    # is that the burst paid no per-actor cold spawns: n spawns would
    # cost >= n * ~0.4s serialized on this box; claims are control-plane
    # only (measured ~0.05s for 4).
    assert dt < 0.4 * n, f"{n} actors took {dt:.2f}s — cold-spawn, not pool claims"
    assert len(set(pids)) == n  # one worker each, all alive
