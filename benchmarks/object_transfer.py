"""Cross-node object data-plane benchmark.

Reference: release/benchmarks/ object_store suite (1 GiB broadcast,
release_logs/*/scalability/object_store.json). Two simulated nodes on
one host; cross-node shm mapping is OFF, so every byte moves through the
chunked network path (agent↔agent TCP).

Usage: python benchmarks/object_transfer.py [--mb 1024] [--iters 3]
"""
from __future__ import annotations

import argparse
import json
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mb", type=int, default=1024)
    p.add_argument("--iters", type=int, default=3)
    args = p.parse_args()

    import numpy as np

    import ray_tpu
    from ray_tpu.core.api import free
    from ray_tpu.core.cluster_utils import Cluster

    cluster = Cluster({"CPU": 2})
    cluster.add_node(num_cpus=2, resources={"remote_node": 10})
    cluster.connect()
    try:
        nbytes = args.mb * 1024 * 1024

        @ray_tpu.remote(resources={"remote_node": 1})
        class Producer:
            def make(self, n):
                return np.ones(n, dtype=np.uint8)

            def consume(self, x):
                return int(x[0])

        prod = Producer.remote()
        ray_tpu.wait_actor_ready(prod)

        # warm up (worker spawn + first transfer path)
        r = prod.make.remote(1024 * 1024)
        ray_tpu.get(r)
        free([r])

        # node → head pull
        rates = []
        for _ in range(args.iters):
            ref = prod.make.remote(nbytes)
            ray_tpu.wait([ref], timeout=600)  # produced (in node store)
            t0 = time.perf_counter()
            arr = ray_tpu.get(ref, timeout=600)
            dt = time.perf_counter() - t0
            assert arr.nbytes == nbytes
            rates.append(nbytes / dt / (1024**3))
            del arr
            free([ref])
        print(json.dumps({
            "benchmark": "cross_node_pull",
            "direction": "node_to_head",
            "mb": args.mb,
            "gib_per_s": round(max(rates), 2),
        }), flush=True)

        # head → node pull
        rates = []
        for _ in range(args.iters):
            data = np.ones(nbytes, dtype=np.uint8)
            ref = ray_tpu.put(data)
            t0 = time.perf_counter()
            assert ray_tpu.get(prod.consume.remote(ref), timeout=600) == 1
            dt = time.perf_counter() - t0
            rates.append(nbytes / dt / (1024**3))
            free([ref])
        print(json.dumps({
            "benchmark": "cross_node_pull",
            "direction": "head_to_node",
            "mb": args.mb,
            "gib_per_s": round(max(rates), 2),
        }), flush=True)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


if __name__ == "__main__":
    main()
