"""Cross-node object data-plane benchmark.

Reference: release/benchmarks/ object_store suite (1 GiB broadcast,
release_logs/*/scalability/object_store.json). Two simulated nodes on
one host; cross-node shm mapping is OFF, so every byte moves through the
chunked network path (agent↔agent TCP).

Usage: python benchmarks/object_transfer.py [--mb 1024] [--iters 3]
"""
from __future__ import annotations

import argparse
import json
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--mb", type=int, default=1024)
    p.add_argument("--iters", type=int, default=3)
    args = p.parse_args()

    import numpy as np

    import ray_tpu
    from ray_tpu.core.api import free
    from ray_tpu.core.cluster_utils import Cluster

    cluster = Cluster({"CPU": 2})
    cluster.add_node(num_cpus=2, resources={"remote_node": 10})
    cluster.connect()
    try:
        nbytes = args.mb * 1024 * 1024

        @ray_tpu.remote(resources={"remote_node": 1})
        class Producer:
            def make(self, n):
                return np.ones(n, dtype=np.uint8)

            def consume(self, x):
                return int(x[0])

        prod = Producer.remote()
        ray_tpu.wait_actor_ready(prod)

        # warm up (worker spawn + first transfer path)
        r = prod.make.remote(1024 * 1024)
        ray_tpu.get(r)
        free([r])

        # node → head pull
        rates = []
        for _ in range(args.iters):
            ref = prod.make.remote(nbytes)
            ray_tpu.wait([ref], timeout=600)  # produced (in node store)
            t0 = time.perf_counter()
            arr = ray_tpu.get(ref, timeout=600)
            dt = time.perf_counter() - t0
            assert arr.nbytes == nbytes
            rates.append(nbytes / dt / (1024**3))
            del arr
            free([ref])
        print(json.dumps({
            "benchmark": "cross_node_pull",
            "direction": "node_to_head",
            "mb": args.mb,
            "gib_per_s": round(max(rates), 2),
        }), flush=True)

        # head → node pull
        rates = []
        for _ in range(args.iters):
            data = np.ones(nbytes, dtype=np.uint8)
            ref = ray_tpu.put(data)
            t0 = time.perf_counter()
            assert ray_tpu.get(prod.consume.remote(ref), timeout=600) == 1
            dt = time.perf_counter() - t0
            rates.append(nbytes / dt / (1024**3))
            free([ref])
        print(json.dumps({
            "benchmark": "cross_node_pull",
            "direction": "head_to_node",
            "mb": args.mb,
            "gib_per_s": round(max(rates), 2),
        }), flush=True)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def broadcast(n_agents: int = 8, mb: int = 1024):
    """1→N distribution: pipelined chain push (rpc object_broadcast,
    reference: push_manager.h / '1 GiB broadcast to 50 nodes in 18.7s')
    vs N INDEPENDENT pulls from the single source. On a real network
    every chain link runs at full NIC rate concurrently; on this 1-core
    single-host sandbox all links share one memory bus + CPU, so the
    honest comparison is aggregate delivered GiB/s for equal bytes.

    Usage: python benchmarks/object_transfer.py broadcast [agents] [mb]
    """
    import numpy as np

    import ray_tpu
    from ray_tpu.core.api import free
    from ray_tpu.core.cluster_utils import Cluster

    cluster = Cluster({"CPU": 1})
    for i in range(n_agents):
        cluster.add_node(num_cpus=1, resources={f"n{i}": 1})
    cluster.connect()
    try:
        nbytes = mb * 1024 * 1024
        core = ray_tpu.core.api._require_worker()
        gib = nbytes / (1024 ** 3)

        @ray_tpu.remote(num_cpus=0.01)
        def consume(x):
            return int(x[0])

        # naive: N independent pulls of the same object from the head
        ref = ray_tpu.put(np.ones(nbytes, dtype=np.uint8))
        t0 = time.perf_counter()
        outs = ray_tpu.get(
            [
                consume.options(resources={f"n{i}": 0.01}).remote(ref)
                for i in range(n_agents)
            ],
            timeout=1800,
        )
        naive_dt = time.perf_counter() - t0
        assert outs == [1] * n_agents
        free([ref])

        # chain: one object_broadcast then the same consumers read locally
        ref = ray_tpu.put(np.ones(nbytes, dtype=np.uint8))
        t0 = time.perf_counter()
        assert core._call("object_broadcast", ref.id, None, timeout=1800) is True
        bcast_dt = time.perf_counter() - t0
        outs = ray_tpu.get(
            [
                consume.options(resources={f"n{i}": 0.01}).remote(ref)
                for i in range(n_agents)
            ],
            timeout=600,
        )
        assert outs == [1] * n_agents
        free([ref])
        print(json.dumps({
            "benchmark": "broadcast_1_to_n",
            "agents": n_agents,
            "mb": mb,
            "naive_concurrent_pulls_s": round(naive_dt, 2),
            "naive_aggregate_gib_per_s": round(n_agents * gib / naive_dt, 2),
            "chain_s": round(bcast_dt, 2),
            "chain_aggregate_gib_per_s": round(n_agents * gib / bcast_dt, 2),
            "speedup": round(naive_dt / bcast_dt, 2),
        }), flush=True)
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "broadcast":
        broadcast(
            int(sys.argv[2]) if len(sys.argv) > 2 else 8,
            int(sys.argv[3]) if len(sys.argv) > 3 else 1024,
        )
    else:
        main()
