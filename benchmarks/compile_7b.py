"""7B north-star config: sharded AOT compile proof.

The single-chip bench (bench.py) runs the largest config one v5e holds;
the BASELINE.json north star is tokens/s/chip AT 7B — which only exists
sharded. This script AOT-compiles the FULL train step (loss + grads +
adamw update, remat, flash attention) for a Llama-2-7B-shaped config
with MeshPlan(fsdp=8) on an 8-device mesh, entirely from abstract
arrays (no 28 GB of host RAM needed), and records XLA's memory analysis
— proving the sharded program compiles and that per-device state fits a
v5e/v5p chip's HBM.

Usage:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
            python benchmarks/compile_7b.py [--out benchmarks/COMPILE_7B.json]
"""
from __future__ import annotations

import argparse
import json
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="")
    args = p.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from ray_tpu.models import transformer as tf
    from ray_tpu.parallel import MeshPlan, build_mesh
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.train_step import make_optimizer, make_train_step

    assert jax.device_count() >= 8, (
        "need 8 devices: run with XLA_FLAGS=--xla_force_host_platform_device_count=8"
    )
    cfg = tf.TransformerConfig(
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=32,
        d_ff=11008,
        max_seq_len=4096,
        dtype=jnp.bfloat16,
        remat=True,
    )
    plan = MeshPlan(fsdp=8)
    mesh = build_mesh(plan)
    opt = make_optimizer(lr=3e-4, warmup=100)

    # Abstract sharded state: eval_shape gives shapes/dtypes; the plan's
    # param/optimizer shardings attach without materializing 28 GB.
    p_shard = mesh_lib.param_shardings(mesh, cfg, plan)
    params_abs = jax.eval_shape(lambda k: tf.init_params(k, cfg), jax.random.PRNGKey(0))
    params_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        params_abs, p_shard,
    )
    n_params = sum(
        int(jnp.prod(jnp.array(a.shape))) for a in jax.tree.leaves(params_abs)
    )
    from ray_tpu.parallel.train_step import _opt_state_shardings

    opt_abs = jax.eval_shape(opt.init, params_abs)
    opt_shard = _opt_state_shardings(opt, params_abs, p_shard, mesh)
    opt_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        opt_abs, opt_shard,
    )
    batch_size, seq = 8, 2048
    batch_abs = {
        "tokens": jax.ShapeDtypeStruct(
            (batch_size, seq + 1), jnp.int32,
            sharding=mesh_lib.batch_sharding(mesh, plan),
        )
    }

    step = make_train_step(cfg, plan, mesh, opt)
    t0 = time.perf_counter()
    lowered = step.lower(params_abs, opt_abs, batch_abs)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    gib = 1 << 30
    out = {
        "artifact": "compile_7b_fsdp8",
        "model_params": n_params,
        "config": {
            "d_model": cfg.d_model, "n_layers": cfg.n_layers,
            "n_heads": cfg.n_heads, "d_ff": cfg.d_ff,
            "seq": seq, "batch": batch_size, "remat": True,
        },
        "plan": plan.sizes(),
        "lower_s": round(t_lower, 1),
        "compile_s": round(t_compile, 1),
        # per-device bytes from XLA's own analysis of the sharded program
        "per_device_argument_gib": round(ma.argument_size_in_bytes / gib, 2),
        "per_device_temp_gib": round(ma.temp_size_in_bytes / gib, 2),
        "per_device_output_gib": round(ma.output_size_in_bytes / gib, 2),
        "per_device_aliased_gib": round(ma.alias_size_in_bytes / gib, 2),
        "per_device_peak_gib": round(
            (ma.argument_size_in_bytes + ma.temp_size_in_bytes
             + ma.output_size_in_bytes - ma.alias_size_in_bytes) / gib, 2
        ),
        "note": (
            "memory analysis is from the CPU backend, whose attention is "
            "the O(S^2) reference path — the TPU build lowers the Pallas "
            "flash kernel (O(S) activation memory), so temp_gib on real "
            "chips is far lower; argument_gib (sharded fsdp=8 state) "
            "transfers directly"
        ),
    }
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
