"""7B north-star config: sharded AOT compile proof.

The single-chip bench (bench.py) runs the largest config one v5e holds;
the BASELINE.json north star is tokens/s/chip AT 7B — which only exists
sharded. This script AOT-compiles the FULL train step (loss + grads +
adamw update, remat, flash attention) for a Llama-2-7B-shaped config
with MeshPlan(fsdp=8) on an 8-device mesh, entirely from abstract
arrays (no 28 GB of host RAM needed), and records XLA's memory analysis
— proving the sharded program compiles and that per-device state fits a
v5e/v5p chip's HBM.

Backends:
  --backend cpu (default): 8 virtual host devices. Run with
      JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
  --backend tpu: compile-only against a REAL TPU topology
      (jax.experimental.topologies — no chips needed), with the Pallas
      flash kernels lowered for TPU. This is the number that proves the
      7B step fits HBM: the CPU backend lowers the O(S^2) reference
      attention instead of the flash kernel and wildly overstates temp
      memory. --topology picks the slice (default v5e:2x4; v5p 16-chip:
      "v5:2x2x4").

Usage:  python benchmarks/compile_7b.py --backend tpu \
            [--topology v5e:2x4] [--out benchmarks/COMPILE_7B_TPU.json]
"""
from __future__ import annotations

import argparse
import json
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="")
    p.add_argument("--backend", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--topology", default="v5e:2x4")
    p.add_argument("--fsdp", type=int, default=8)
    p.add_argument("--tp", type=int, default=1)
    args = p.parse_args()

    import os

    import jax

    # Host platform is CPU either way (no TPU runtime claimed); the tpu
    # backend compiles against the TOPOLOGY below.
    jax.config.update("jax_platforms", "cpu")
    topo_devices = None
    if args.backend == "tpu":
        # AOT against the target topology (reference for the technique:
        # jax.experimental.topologies + AheadOfTimeLowering). The default
        # backend is CPU at trace time, so the flash-kernel dispatch must
        # be forced to the TPU lowering explicitly — otherwise the
        # O(S^2) reference attention gets lowered and the memory numbers
        # overstate temp by gigabytes.
        os.environ["RAY_TPU_FORCE_PALLAS"] = "1"
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(args.topology, platform="tpu")
        topo_devices = list(topo.devices)
        need = args.fsdp * args.tp
        assert len(topo_devices) >= need, (
            f"topology {args.topology} has {len(topo_devices)} chips < {need}"
        )
        topo_devices = topo_devices[:need]
    import jax.numpy as jnp

    from ray_tpu.models import transformer as tf
    from ray_tpu.parallel import MeshPlan, build_mesh
    from ray_tpu.parallel import mesh as mesh_lib
    from ray_tpu.parallel.train_step import make_optimizer, make_train_step

    if topo_devices is None:
        need = args.fsdp * args.tp
        assert jax.device_count() == need, (
            f"need exactly fsdp*tp={need} devices: run with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={need}"
        )
    cfg = tf.TransformerConfig.llama7b(
        max_seq_len=4096, dtype=jnp.bfloat16, remat=True
    )
    plan = MeshPlan(fsdp=args.fsdp, tp=args.tp)
    mesh = build_mesh(plan, devices=topo_devices)
    opt = make_optimizer(lr=3e-4, warmup=100)

    # Abstract sharded state: eval_shape gives shapes/dtypes; the plan's
    # param/optimizer shardings attach without materializing 28 GB.
    p_shard = mesh_lib.param_shardings(mesh, cfg, plan)
    params_abs = jax.eval_shape(lambda k: tf.init_params(k, cfg), jax.random.PRNGKey(0))
    params_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        params_abs, p_shard,
    )
    n_params = sum(
        int(jnp.prod(jnp.array(a.shape))) for a in jax.tree.leaves(params_abs)
    )
    from ray_tpu.parallel.train_step import _opt_state_shardings

    opt_abs = jax.eval_shape(opt.init, params_abs)
    opt_shard = _opt_state_shardings(opt, params_abs, p_shard, mesh)
    opt_abs = jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        opt_abs, opt_shard,
    )
    # batch is a multiple of the (dp=1, fsdp) data axes and >= 8
    batch_size, seq = args.fsdp * max(1, -(-8 // args.fsdp)), 2048
    batch_abs = {
        "tokens": jax.ShapeDtypeStruct(
            (batch_size, seq + 1), jnp.int32,
            sharding=mesh_lib.batch_sharding(mesh, plan),
        )
    }

    step = make_train_step(cfg, plan, mesh, opt)
    t0 = time.perf_counter()
    lowered = step.lower(params_abs, opt_abs, batch_abs)
    t_lower = time.perf_counter() - t0
    t0 = time.perf_counter()
    compiled = lowered.compile()
    t_compile = time.perf_counter() - t0
    ma = compiled.memory_analysis()
    gib = 1 << 30
    out = {
        "artifact": f"compile_7b_fsdp{args.fsdp}_tp{args.tp}_{args.backend}"
        + (f"_{args.topology.replace(':', '_')}" if args.backend == "tpu" else ""),
        "backend": args.backend,
        "topology": args.topology if args.backend == "tpu" else None,
        "model_params": n_params,
        "config": {
            "d_model": cfg.d_model, "n_layers": cfg.n_layers,
            "n_heads": cfg.n_heads, "d_ff": cfg.d_ff,
            "seq": seq, "batch": batch_size, "remat": True,
        },
        "plan": plan.sizes(),
        "lower_s": round(t_lower, 1),
        "compile_s": round(t_compile, 1),
        # per-device bytes from XLA's own analysis of the sharded program
        "per_device_argument_gib": round(ma.argument_size_in_bytes / gib, 2),
        "per_device_temp_gib": round(ma.temp_size_in_bytes / gib, 2),
        "per_device_output_gib": round(ma.output_size_in_bytes / gib, 2),
        "per_device_aliased_gib": round(ma.alias_size_in_bytes / gib, 2),
        "per_device_peak_gib": round(
            (ma.argument_size_in_bytes + ma.temp_size_in_bytes
             + ma.output_size_in_bytes - ma.alias_size_in_bytes) / gib, 2
        ),
        "note": (
            "TPU backend: memory analysis is XLA's own HBM accounting for "
            "the target topology with the Pallas flash kernels lowered — "
            "the definitive per-chip number."
            if args.backend == "tpu"
            else
            "memory analysis is from the CPU backend, whose attention is "
            "the O(S^2) reference path — the TPU build lowers the Pallas "
            "flash kernel (O(S) activation memory); see COMPILE_7B_TPU.json "
            "for the TPU-backend number"
        ),
    }
    out["fits"] = True  # reaching here means XLA accepted the program
    line = json.dumps(out)
    print(line, flush=True)
    if args.out:
        # APPEND one JSON line per run — the committed artifact is the
        # JSONL of the topology matrix (see RESULTS.md reproduce line)
        with open(args.out, "a") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
