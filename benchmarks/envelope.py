"""Scale-envelope shapes from the reference's release benchmarks
(reference: release/benchmarks/README.md — 40k actors, 1M queued tasks,
1k PGs on a 64-node cluster; BASELINE.md table).

This sandbox is ONE core, so process-bound rows (live actors == worker
processes) hit the host's spawn/memory wall long before the
controller's data structures do — each row therefore reports either the
measured envelope number or the documented breaking point, plus the
controller-loop p50 latency while holding the load (the health metric
for the single-asyncio-loop design).

Each row also carries the control-plane flight recorder's per-phase
breakdown (``phases``: p50/p95/p99 dwell per lifecycle state, e.g.
``task.SUBMITTED`` = submission handling + dep resolution,
``task.QUEUED`` = waiting for lease/worker capacity, ``lease.REQUESTED``
= lease scheduling latency, ``task.RUNNING`` = execution; plus
``pending_reasons`` — why-pending attribution deltas for the row) so a
stalled depth says WHICH stage to attack. ``--no-recorder`` disables
the recorder for A/B overhead runs.

Usage: python benchmarks/envelope.py [--queued 100000] [--pgs 1000]
           [--actor-records 10000] [--live-actors 60] [--churn 20000]
           [--no-recorder] [--no-memory-census]
           [--out benchmarks/ENVELOPE_r03.json]
"""
from __future__ import annotations

# ray-tpu: lint-ignore-file[RTL007] — benchmark CLI: stdout JSON rows
# (and the log-churn arm's deliberately chatty prints) ARE the output
# contract, not package logging.

import argparse
import json
import statistics
import threading
import time

_prev_reasons: dict = {}


def lifecycle_phases() -> dict:
    """Per-phase dwell breakdown from the flight recorder: p50/p95/p99 ms
    per (kind, state) over the recorder's bounded sample rings (recent-
    dominated), plus the why-pending attribution DELTA since the previous
    row. Empty when the recorder is disabled (--no-recorder)."""
    global _prev_reasons
    from ray_tpu.util import state as state_api

    snap = state_api.summarize_lifecycle()
    if not snap.get("enabled"):
        return {}
    phases = {}
    for kind, states in snap.get("states", {}).items():
        for st, info in states.items():
            row = {"count": info.get("count", 0)}
            d = info.get("dwell_ms") or {}
            for k in ("p50", "p95", "p99"):
                if k in d:
                    row[k] = d[k]
            phases[f"{kind}.{st}"] = row
    reasons = snap.get("pending_reasons", {})
    delta = {
        k: v - _prev_reasons.get(k, 0)
        for k, v in reasons.items()
        if v - _prev_reasons.get(k, 0) > 0
    }
    _prev_reasons = dict(reasons)
    return {"phases": phases, "pending_reasons": delta}


class LoopProbe:
    """Samples controller-loop latency (KV round-trips) on a side thread."""

    def __init__(self):
        self.samples = []
        self._stop = threading.Event()
        self._thread = None

    def __enter__(self):
        from ray_tpu.core.api import _require_worker

        core = _require_worker()

        def run():
            while not self._stop.is_set():
                t = time.perf_counter()
                try:
                    core.kv_get("envelope", b"probe")
                except Exception:  # noqa: BLE001 — shutdown race
                    return
                self.samples.append(time.perf_counter() - t)
                time.sleep(0.05)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=2)

    def stats(self) -> dict:
        if not self.samples:
            return {}
        ms = sorted(x * 1e3 for x in self.samples)
        return {
            "loop_p50_ms": round(statistics.median(ms), 1),
            "loop_p99_ms": round(ms[int(0.99 * (len(ms) - 1))], 1),
        }


def controller_rss_mb() -> float:
    import os

    # the controller is this session's parent-owned process; find by cmdline
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read()
            if b"ray_tpu.core.controller" in cmd:
                with open(f"/proc/{pid}/status") as f:
                    for line in f:
                        if line.startswith("VmRSS"):
                            return round(int(line.split()[1]) / 1024, 1)
        except OSError:
            continue
    return -1.0


def bench_queued_tasks(n: int) -> dict:
    """Queue-depth envelope (reference: 1M+ tasks queued on one node,
    drained in 188.9s). Tasks are queued caller-side under the lease
    path; the controller sees only lease traffic."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def noop():
        return 0

    with LoopProbe() as probe:
        t0 = time.perf_counter()
        refs = [noop.remote() for _ in range(n)]
        submit_dt = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = ray_tpu.get(refs, timeout=3600)
        drain_dt = time.perf_counter() - t0
    assert len(out) == n
    return {
        "benchmark": "queued_tasks",
        "n": n,
        "submit_per_s": round(n / submit_dt, 1),
        "drain_per_s": round(n / drain_dt, 1),
        "drain_s": round(drain_dt, 1),
        "controller_rss_mb": controller_rss_mb(),
        **probe.stats(),
    }


def bench_actor_records(n: int) -> dict:
    """Controller data structures at 10k ACTOR RECORDS (reference: 40k
    actors cluster-wide). Live actors are worker processes — impossible
    at 10k on one core — so this registers n actor records whose
    creation stays pending on an infeasible resource: the controller
    holds n ActorRecords + n pending creation tasks and must stay
    responsive, then clean all of them up on kill."""
    import ray_tpu

    @ray_tpu.remote(resources={"GHOST": 1})
    class Ghost:
        def ping(self):
            return 0

    with LoopProbe() as probe:
        t0 = time.perf_counter()
        actors = [Ghost.remote() for _ in range(n)]
        reg_dt = time.perf_counter() - t0
        time.sleep(2.0)  # controller pump cycles with n pending records
        holding = dict(probe.stats())
        rss = controller_rss_mb()
        t0 = time.perf_counter()
        for a in actors:
            ray_tpu.kill(a)
        kill_dt = time.perf_counter() - t0
    rows = ray_tpu.core.api._require_worker().list_state("actors")
    alive = sum(1 for r in rows if r["state"] not in ("DEAD",))
    return {
        "benchmark": "actor_records",
        "n": n,
        "register_per_s": round(n / reg_dt, 1),
        "kill_per_s": round(n / kill_dt, 1),
        "alive_after_kill": alive,
        "controller_rss_mb": rss,
        **{f"holding_{k}": v for k, v in holding.items()},
    }


def bench_live_actors(n: int) -> dict:
    """Live actors = real worker processes. Two phases: WARM the direct
    pool to ~n workers (paying the host's process-spawn wall once), then
    measure actor creation CLAIMING pooled workers — the claim path is
    control-plane-only (reference: PopWorker serves actors too,
    worker_pool.h:363-374). ``actors_per_s`` is the warm-claim rate;
    ``cold_spawn_s`` reports what the warm-up itself cost."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.001)
    def warm_nap():
        time.sleep(3.0)
        return 0

    t0 = time.perf_counter()
    ray_tpu.get([warm_nap.remote() for _ in range(n)], timeout=1800)
    warm_dt = time.perf_counter() - t0

    @ray_tpu.remote(num_cpus=0.001)
    class A:
        def ping(self):
            return 0

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=1800)
    dt = time.perf_counter() - t0
    out = {
        "benchmark": "live_actors",
        "n": n,
        "actors_per_s": round(n / dt, 2),
        "cold_spawn_s": round(warm_dt, 2),
        "controller_rss_mb": controller_rss_mb(),
        "note": "warm-pool claim rate; pool warm-up (process spawn) reported separately",
    }
    for a in actors:
        ray_tpu.kill(a)
    return out


def bench_live_pgs(n: int) -> dict:
    """1k placement groups HELD simultaneously (reference envelope:
    1,000+ simultaneously running PGs)."""
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    with LoopProbe() as probe:
        t0 = time.perf_counter()
        pgs = [placement_group([{"CPU": 0.001}], strategy="PACK") for _ in range(n)]
        for pg in pgs:
            assert pg.ready(timeout=60)
        create_dt = time.perf_counter() - t0
        holding = dict(probe.stats())
        rss = controller_rss_mb()
        t0 = time.perf_counter()
        for pg in pgs:
            remove_placement_group(pg)
        remove_dt = time.perf_counter() - t0
    return {
        "benchmark": "live_pgs",
        "n": n,
        "create_per_s": round(n / create_dt, 1),
        "remove_per_s": round(n / remove_dt, 1),
        "controller_rss_mb": rss,
        **{f"holding_{k}": v for k, v in holding.items()},
    }


def bench_object_churn(n: int, census_ab: bool = True) -> dict:
    """Put/free storm through the object directory (reference: the
    object-store half of the release benchmarks — many small objects
    created and released at rate). Holds a sliding window of refs so the
    controller sees creates, holder flushes, AND frees concurrently.

    When ``census_ab`` is set, the driver-side memory-census capture
    (call-site stack walk + intern at every put — the per-operation cost
    the census adds) is A/B'd interleaved best-of-2; the budget is <=3%
    like profiling (``census_overhead_ok``). Controller-side attribution
    rides the same RPCs either way and is not separable per-process."""
    import collections

    import ray_tpu
    from ray_tpu.core import memory_census

    payload = b"c" * 4096  # inline tier: every put is one directory RPC

    def one_arm(count: int) -> float:
        window = collections.deque()
        t0 = time.perf_counter()
        for _ in range(count):
            window.append(ray_tpu.put(payload))
            if len(window) >= 64:
                ray_tpu.free([window.popleft()])
        ray_tpu.free(list(window))
        window.clear()
        return count / (time.perf_counter() - t0)

    one_arm(min(500, n))  # warm the put path / intern cache
    arms = {"on": 0.0, "off": 0.0}
    rounds = 2 if census_ab else 1
    with LoopProbe() as probe:
        for _ in range(rounds):  # interleaved best-of-N
            if census_ab:
                # toggle ONLY inside the A/B: with --no-memory-census the
                # cluster-config disable must stay in force for this arm
                # and every later bench row
                memory_census.set_enabled(False)
                arms["off"] = max(arms["off"], one_arm(n))
                memory_census.set_enabled(True)
            arms["on"] = max(arms["on"], one_arm(n))
    row = {
        "benchmark": "object_churn",
        "n": n,
        "puts_per_s": round(arms["on"], 1),
        "controller_rss_mb": controller_rss_mb(),
        **probe.stats(),
    }
    if census_ab:
        overhead = 100.0 * (arms["off"] - arms["on"]) / max(arms["off"], 1e-9)
        row["puts_per_s_no_census"] = round(arms["off"], 1)
        row["census_overhead_pct"] = round(max(0.0, overhead), 2)
        row["census_overhead_ok"] = overhead <= 3.0
    return row


def bench_log_churn(n_tasks: int, lines: int, work: int = 20000,
                    ab: bool = True) -> dict:
    """Log-churn arm: N concurrent tasks, each emitting M log lines at a
    realistic rate (every line paired with ``work`` iterations of a small
    compute kernel — chatty-but-working tasks, not a bare print loop),
    with structured capture on vs off (interleaved best-of-2, the census
    arm's shape). The "off" arm prints to ``sys.__stdout__`` — the
    pre-proxy stream over the SAME redirected log file — so the delta
    isolates exactly the log plane's per-line machinery (record build +
    attribution + sidecar append + ship check); budget <=3% of task wall
    like profiling/census."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=0.001)
    def chatter(n, w, structured):
        import sys

        stream = sys.stdout if structured else sys.__stdout__
        payload = "x" * 64
        acc = 0
        for i in range(n):
            for j in range(w):
                acc += j & 7
            print(f"log-churn line {i} {payload} {acc & 1}", file=stream)
        return n

    def one_arm(structured) -> float:
        """Total wall for the N-task wave (the overhead denominator)."""
        import ray_tpu as rt

        t0 = time.perf_counter()
        out = rt.get(
            [chatter.remote(lines, work, structured) for _ in range(n_tasks)],
            timeout=900,
        )
        dt = time.perf_counter() - t0
        assert sum(out) == n_tasks * lines
        return dt

    one_arm(True)  # warm the worker pool + capture path
    arms = {"on": float("inf"), "off": float("inf")}
    rounds = 2 if ab else 1
    with LoopProbe() as probe:
        for _ in range(rounds):  # interleaved best-of-N (min wall)
            if ab:
                arms["off"] = min(arms["off"], one_arm(False))
            arms["on"] = min(arms["on"], one_arm(True))
    total_lines = n_tasks * lines
    row = {
        "benchmark": "log_churn",
        "tasks": n_tasks,
        "lines_per_task": lines,
        "work_per_line": work,
        "lines_per_s": round(total_lines / arms["on"], 1),
        "controller_rss_mb": controller_rss_mb(),
        **probe.stats(),
    }
    if ab:
        overhead = 100.0 * (arms["on"] - arms["off"]) / max(arms["off"], 1e-9)
        row["lines_per_s_no_structured"] = round(total_lines / arms["off"], 1)
        row["log_overhead_pct"] = round(max(0.0, overhead), 2)
        row["log_overhead_ok"] = overhead <= 3.0
    return row


def bench_train_chaos(scenario: str, steps: int = 12) -> dict:
    """Elastic-gang MTTR arm: a 2-worker gang across per-worker nodes,
    one train host SIGKILLed mid-run (after checkpoint 1 commits, so the
    kill provably lands between steps). Reports the recovery machinery's
    own detect/repair/resume breakdown plus steps lost to the kill.

    ``scenario``: "rejoin" (a spare node is available — replacement
    worker, same world size) or "remesh" (no spare, min_workers=1 —
    shrink to the survivor). Runs under its own multi-node cluster; call
    after the shared-init rows have shut down."""
    import os
    import shutil
    import signal
    import tempfile
    import threading

    from ray_tpu.core.cluster_utils import Cluster
    from ray_tpu.train import (
        FailureConfig,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )

    spare = 1 if scenario == "rejoin" else 0
    cluster = Cluster(head_resources={"CPU": 1})
    storage = tempfile.mkdtemp(prefix=f"chaos_{scenario}_")
    try:
        for _ in range(2 + spare):
            cluster.add_node(num_cpus=2)
        cluster.connect()

        def loop(config):
            import os as _os
            import tempfile as _tf
            import time as _t

            import numpy as _np

            from ray_tpu import train

            ctx = train.get_context()
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt is not None:
                with ckpt.as_directory() as d:
                    start = int(_np.load(_os.path.join(d, "step.npy"))) + 1
            for step in range(start, config["steps"]):
                _t.sleep(0.25)
                with _tf.TemporaryDirectory() as d:
                    if ctx.get_world_rank() == 0:
                        _np.save(_os.path.join(d, "step.npy"),
                                 _np.int64(step))
                    train.report(
                        {"step": step, "ws": ctx.get_world_size(),
                         "resumed_from": start},
                        checkpoint=train.Checkpoint.from_directory(d),
                    )

        scaling_kw = {"min_workers": 1} if scenario == "remesh" else {}
        trainer = JaxTrainer(
            loop,
            train_loop_config={"steps": steps},
            scaling_config=ScalingConfig(
                num_workers=2, resources_per_worker={"CPU": 2},
                **scaling_kw,
            ),
            run_config=RunConfig(
                name=scenario, storage_path=storage,
                failure_config=FailureConfig(
                    max_failures=2,
                    elastic_grace_s=15.0 if spare else 2.0,
                ),
            ),
        )
        run_dir = os.path.join(storage, scenario)

        def chaos():
            from ray_tpu.util import state as state_api

            marker = os.path.join(run_dir, "checkpoint_000001", ".complete")
            deadline = time.time() + 120
            while time.time() < deadline and not os.path.exists(marker):
                time.sleep(0.05)
            hosts = {
                w["node_id"] for w in state_api.list_workers()
                if w.get("state") == "ACTOR"
            }
            for h in cluster._nodes:
                if h.node_id_hex in hosts:
                    h.proc.send_signal(signal.SIGKILL)
                    return

        killer = threading.Thread(target=chaos, daemon=True)
        killer.start()
        t0 = time.perf_counter()
        result = trainer.fit()
        wall = time.perf_counter() - t0
        killer.join(timeout=10)
        assert result.error is None, result.error
        assert result.recoveries, "kill never triggered a recovery"
        rec = result.recoveries[0]
        # steps_lost = work the dead incarnation reported that the
        # resumed one re-ran: its furthest step vs the resume point.
        resumed_from = result.metrics.get("resumed_from", 0)
        prev = [
            m["step"] for m in result.metrics_history
            if m.get("resumed_from", 0) < resumed_from
        ]
        steps_lost = max(prev, default=resumed_from - 1) - resumed_from + 1
        mttr = sum(
            rec.get(k) or 0.0
            for k in ("detect_ms", "repair_ms", "resume_ms")
        )
        row = {
            "benchmark": f"train_chaos_{scenario}",
            "steps": steps,
            "mode": rec.get("mode"),
            "detect_ms": rec.get("detect_ms"),
            "repair_ms": rec.get("repair_ms"),
            "resume_ms": rec.get("resume_ms"),
            "mttr_ms": round(mttr, 1),
            "steps_lost": max(0, steps_lost),
            "world_size_after": result.metrics.get("ws"),
            "final_step": result.metrics.get("step"),
            "wall_s": round(wall, 1),
        }
        row.update(lifecycle_phases())
        return row
    finally:
        cluster.shutdown()
        shutil.rmtree(storage, ignore_errors=True)


def _drain_noops(n: int) -> float:
    """Submit+drain n single-CPU noops; returns drain throughput/s."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1)
    def noop():
        return 0

    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    out = ray_tpu.get(refs, timeout=3600)
    dt = time.perf_counter() - t0
    assert len(out) == n
    return n / dt


def bench_lease_ab(n: int, rounds: int = 2) -> dict:
    """Round-17 on/off A/B: the same queued drain under the batched
    lease/push control plane vs the legacy per-task lease path
    (``lease_batching: False``). The kill-switch is cluster config, so
    each arm is its own init; arms interleave B/L/B/L so box drift hits
    both equally."""
    import ray_tpu

    arms = {"batched": [], "legacy": []}
    for _ in range(rounds):
        for name, flag in (("batched", True), ("legacy", False)):
            ray_tpu.init(num_cpus=8, _system_config={"lease_batching": flag})
            try:
                arms[name].append(_drain_noops(n))
            finally:
                ray_tpu.shutdown()
    batched = statistics.median(arms["batched"])
    legacy = statistics.median(arms["legacy"])
    return {
        "benchmark": "lease_ab",
        "n": n,
        "rounds": rounds,
        "batched_drain_per_s": round(batched, 1),
        "legacy_drain_per_s": round(legacy, 1),
        "speedup": round(batched / legacy, 2),
    }


def bench_recorder_ab(n: int, rounds: int = 2) -> dict:
    """Recorder-overhead A/B on the batched path: with the flight
    recorder (batch ingestion, round 17) vs ``lifecycle_events: False``.
    Budget: the recorder may cost at most 3% of drain throughput."""
    import ray_tpu

    arms = {"on": [], "off": []}
    for _ in range(rounds):
        for name, flag in (("on", True), ("off", False)):
            ray_tpu.init(num_cpus=8, _system_config={"lifecycle_events": flag})
            try:
                arms[name].append(_drain_noops(n))
            finally:
                ray_tpu.shutdown()
    on = statistics.median(arms["on"])
    off = statistics.median(arms["off"])
    overhead_pct = max(0.0, (off - on) / off * 100.0)
    return {
        "benchmark": "recorder_ab",
        "n": n,
        "rounds": rounds,
        "recorder_on_drain_per_s": round(on, 1),
        "recorder_off_drain_per_s": round(off, 1),
        "recorder_overhead_pct": round(overhead_pct, 2),
        "recorder_overhead_ok": overhead_pct <= 3.0,
    }


# Seeded slow-node plan (--slow-node-seed): jitters the driver's control
# RPCs — lease grants and batched pushes — so the scale arms re-run
# under exactly-replayable link jitter. Deterministic given the seed.
_SLOW_NODE_RULES = [
    {"method": "lease_batch", "direction": "out", "action": "delay",
     "delay_ms": 40.0, "probability": 0.25},
    {"method": "lease_worker*", "direction": "out", "action": "delay",
     "delay_ms": 40.0, "probability": 0.25},
    {"method": "push_task*", "direction": "out", "action": "delay",
     "delay_ms": 20.0, "probability": 0.15},
]


def bench_health_actuator(churn: int = 4000) -> dict:
    """Self-healing arm (the health plane's envelope): a seeded
    store-pressure plan against a deliberately small store measures the
    plane's detect→act latency (threshold crossing → ``pressure_spill``
    acted), the post-act occupancy it leaves, and post-act recovery
    (every proactively spilled object restores byte-equal); then an
    on/off A/B of the same put/get churn prices the always-on health
    plane — the detector sites + engine tick ride the telemetry sweep,
    so the budget is ≤3% like the other observability legs
    (``actuator_overhead_ok``). Runs under its own inits (the actuator
    kill-switch is cluster config)."""
    import os

    import ray_tpu
    from ray_tpu.util import state as state_api

    # -- seeded pressure plan: detect→act latency + recovery ------------
    ray_tpu.init(
        num_cpus=2,
        object_store_memory=8 * 1024 * 1024,
        _system_config={
            "node_telemetry_interval_ms": 100,
            "memory_incident_occupancy_pct": 0.5,
            "health_spill_target_pct": 0.3,
            "health_action_cooldown_s": 300.0,
            "profiling_incidents": False,
        },
    )
    try:
        blobs = [os.urandom(256 * 1024) for _ in range(18)]  # ~56% of cap
        t0 = time.perf_counter()
        refs = [ray_tpu.put(b) for b in blobs]
        acted = None
        deadline = time.time() + 30
        while time.time() < deadline and acted is None:
            for r in state_api.summarize_health().get("actions_recent", []):
                if (r["actuator"] == "pressure_spill"
                        and r["outcome"] == "acted"):
                    acted = r
                    break
            if acted is None:
                time.sleep(0.02)
        detect_act_ms = (time.perf_counter() - t0) * 1e3
        assert acted, "pressure_spill never acted"
        t1 = time.perf_counter()
        for ref, blob in zip(refs, blobs):
            assert ray_tpu.get(ref, timeout=30) == blob
        recover_ms = (time.perf_counter() - t1) * 1e3
    finally:
        ray_tpu.shutdown()

    # -- on/off A/B: the price of the always-on plane -------------------
    payload = b"h" * 4096

    def one_init(enabled: bool) -> float:
        ray_tpu.init(
            num_cpus=2,
            _system_config={
                "health_actuators": enabled,
                "node_telemetry_interval_ms": 200,
                "profiling_incidents": False,
            },
        )
        try:
            best = 0.0
            for _ in range(2):  # best-of-2 inside one cluster
                window = []
                t0 = time.perf_counter()
                for _ in range(churn):
                    window.append(ray_tpu.put(payload))
                    if len(window) >= 64:
                        ray_tpu.free(window)
                        window = []
                ray_tpu.free(window)
                best = max(best, churn / (time.perf_counter() - t0))
            return best
        finally:
            ray_tpu.shutdown()

    off = one_init(False)
    on = one_init(True)
    overhead = 100.0 * (off - on) / max(off, 1e-9)
    return {
        "benchmark": "health_actuator",
        "detect_act_ms": round(detect_act_ms, 1),
        "spilled": acted["detail"].get("spilled"),
        "post_act_occupancy": acted["detail"].get("occupancy"),
        "recover_ms": round(recover_ms, 1),
        "churn": churn,
        "puts_per_s": round(on, 1),
        "puts_per_s_no_health": round(off, 1),
        "actuator_overhead_pct": round(max(0.0, overhead), 2),
        "actuator_overhead_ok": overhead <= 3.0,
    }


def bench_checkpoint_ab(payload_mb: int = 32, steps: int = 3,
                        store_mbps: float = 16.0) -> dict:
    """Non-blocking checkpoint A/B: the same single-worker loop
    checkpointing a ``payload_mb`` state, sync vs async upload, with the
    persistent store throttled to ``store_mbps`` MB/s via the cloudfs
    seam (models remote-storage bandwidth; the async arm's host-side
    staging snapshot stays at disk speed). The step-time stall is the
    in-loop wall of ``train.report`` — budget: async stall <= 10% of the
    synchronous checkpoint cost."""
    import shutil
    import statistics as stats
    import tempfile

    import ray_tpu
    from ray_tpu.train import (
        CheckpointConfig,
        JaxTrainer,
        RunConfig,
        ScalingConfig,
    )

    storage = tempfile.mkdtemp(prefix="ckpt_ab_")
    env = {
        "RAY_TPU_CLOUDFS_THROTTLE_PATH": storage,
        "RAY_TPU_CLOUDFS_THROTTLE_MBPS": str(store_mbps),
    }

    def loop(config):
        import os as _os
        import tempfile as _tf
        import time as _t

        import numpy as _np

        from ray_tpu import train

        arr = _np.zeros(config["payload_mb"] * 262144, _np.float32)
        prev_ms = 0.0
        for step in range(config["steps"]):
            with _tf.TemporaryDirectory() as d:
                _np.save(_os.path.join(d, "w.npy"), arr)
                t0 = _t.monotonic()
                train.report(
                    {"step": step, "prev_report_ms": prev_ms},
                    checkpoint=train.Checkpoint.from_directory(d),
                )
                prev_ms = (_t.monotonic() - t0) * 1000.0

    ray_tpu.init(num_cpus=4)
    arms = {}
    try:
        for arm, async_upload in (("sync", False), ("async", True)):
            trainer = JaxTrainer(
                loop,
                train_loop_config={"payload_mb": payload_mb,
                                   "steps": steps},
                scaling_config=ScalingConfig(
                    num_workers=1, resources_per_worker={"CPU": 1},
                    worker_env=env,
                ),
                run_config=RunConfig(
                    name=f"ckpt_{arm}", storage_path=storage,
                    checkpoint_config=CheckpointConfig(
                        async_upload=async_upload
                    ),
                ),
            )
            t0 = time.perf_counter()
            result = trainer.fit()
            wall = time.perf_counter() - t0
            assert result.error is None, result.error
            stalls = [
                m["prev_report_ms"] for m in result.metrics_history
                if m["step"] >= 1
            ]
            arms[arm] = {"stall_ms": stats.mean(stalls), "wall_s": wall}
    finally:
        ray_tpu.shutdown()
        shutil.rmtree(storage, ignore_errors=True)
    stall_pct = 100.0 * arms["async"]["stall_ms"] / max(
        arms["sync"]["stall_ms"], 1e-9
    )
    return {
        "benchmark": "checkpoint_async_ab",
        "payload_mb": payload_mb,
        "steps": steps,
        "store_mbps": store_mbps,
        "sync_report_stall_ms": round(arms["sync"]["stall_ms"], 1),
        "async_report_stall_ms": round(arms["async"]["stall_ms"], 1),
        "async_stall_pct_of_sync": round(stall_pct, 2),
        "async_stall_ok": stall_pct <= 10.0,
        "sync_wall_s": round(arms["sync"]["wall_s"], 1),
        "async_wall_s": round(arms["async"]["wall_s"], 1),
    }


def main():
    import ray_tpu

    p = argparse.ArgumentParser()
    p.add_argument("--queued", type=int, default=100000)
    p.add_argument("--pgs", type=int, default=1000)
    p.add_argument("--actor-records", type=int, default=10000)
    p.add_argument("--live-actors", type=int, default=60)
    p.add_argument("--churn", type=int, default=20000)
    p.add_argument(
        "--no-recorder", action="store_true",
        help="disable the control-plane flight recorder (A/B overhead runs)",
    )
    p.add_argument(
        "--no-memory-census", action="store_true",
        help="disable memory-census attribution cluster-wide (A/B runs; "
             "the churn row then skips its built-in driver-side A/B)",
    )
    p.add_argument("--log-tasks", type=int, default=8,
                   help="log-churn arm: concurrent chatty tasks")
    p.add_argument("--log-lines", type=int, default=4000,
                   help="log-churn arm: print lines per task")
    p.add_argument("--log-work", type=int, default=20000,
                   help="log-churn arm: compute-kernel iterations per line "
                        "(paces emission — chatty tasks still do work)")
    p.add_argument(
        "--no-log-structured", action="store_true",
        help="disable structured log capture cluster-wide (A/B runs; the "
             "log-churn row then skips its built-in stream-level A/B)",
    )
    p.add_argument("--no-chaos", action="store_true",
                   help="skip the train-chaos MTTR + checkpoint A/B arms")
    p.add_argument("--no-health", action="store_true",
                   help="skip the self-healing actuator arm")
    p.add_argument("--health-churn", type=int, default=4000,
                   help="actuator arm: puts per A/B round")
    p.add_argument("--chaos-steps", type=int, default=12,
                   help="chaos arms: train steps per scenario")
    p.add_argument("--ckpt-mb", type=int, default=32,
                   help="checkpoint A/B: checkpoint payload size (MB)")
    p.add_argument("--ckpt-store-mbps", type=float, default=16.0,
                   help="checkpoint A/B: simulated store bandwidth (MB/s)")
    p.add_argument("--lease-ab", type=int, default=10000,
                   help="lease-batching on/off A/B arm: tasks per round "
                        "(0 = skip)")
    p.add_argument("--recorder-ab", type=int, default=10000,
                   help="recorder-overhead A/B arm: tasks per round "
                        "(0 = skip)")
    p.add_argument("--slow-node-seed", type=int, default=0,
                   help="install a seeded FaultSchedule slow-node delay "
                        "plan in the driver for the shared-init rows "
                        "(0 = off); rows are tagged with the seed")
    p.add_argument("--out", default="")
    args = p.parse_args()

    overrides = {}
    if args.no_recorder:
        overrides["lifecycle_events"] = False
    if args.no_memory_census:
        overrides["memory_census"] = False
    if args.no_log_structured:
        overrides["log_structured"] = False
    # Logical CPUs sized so the lease ramp can hold --live-actors
    # concurrent warm-up naps (worker pool caps scale with CPU count).
    ray_tpu.init(
        num_cpus=max(8, args.live_actors + 4),
        _system_config=overrides or None,
    )
    if args.slow_node_seed:
        from ray_tpu.util import chaos

        chaos.install_fault_plan(
            {"seed": args.slow_node_seed, "rules": _SLOW_NODE_RULES}
        )
    rows = []
    try:
        for fn, fnargs, fnkw in (
            (bench_live_pgs, (args.pgs,), {}),
            (bench_actor_records, (args.actor_records,), {}),
            (bench_live_actors, (args.live_actors,), {}),
            (bench_object_churn, (args.churn,),
             {"census_ab": not args.no_memory_census}),
            (bench_log_churn, (args.log_tasks, args.log_lines),
             {"work": args.log_work, "ab": not args.no_log_structured}),
            (bench_queued_tasks, (args.queued,), {}),
        ):
            row = fn(*fnargs, **fnkw)
            row.update(lifecycle_phases())
            if args.slow_node_seed:
                row["slow_node_seed"] = args.slow_node_seed
            rows.append(row)
            print(json.dumps(row), flush=True)
    finally:
        ray_tpu.shutdown()
        if args.slow_node_seed:
            from ray_tpu.util import chaos

            chaos.install_fault_plan(None)
    if args.lease_ab:
        row = bench_lease_ab(args.lease_ab)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.recorder_ab:
        row = bench_recorder_ab(args.recorder_ab)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if not args.no_chaos:
        # Chaos arms manage their own cluster lifecycles (the MTTR arms
        # need per-worker HOST processes to kill) — run them after the
        # shared-init rows shut down.
        for scenario in ("rejoin", "remesh"):
            row = bench_train_chaos(scenario, steps=args.chaos_steps)
            rows.append(row)
            print(json.dumps(row), flush=True)
        row = bench_checkpoint_ab(
            args.ckpt_mb, store_mbps=args.ckpt_store_mbps
        )
        rows.append(row)
        print(json.dumps(row), flush=True)
    if not args.no_health:
        # Own inits: the actuator kill-switch is cluster config.
        row = bench_health_actuator(args.health_churn)
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
