"""Control-plane scalability benchmarks.

Reference: release/benchmarks/ (many_tasks / many_actors / many_pgs,
README.md:1-34) and release/microbenchmark — nightly suites whose JSON
results are archived per release (release_logs/<version>/). Same shape
here: each scenario prints one JSON line; run the module for the full
suite. Numbers are single-host (the reference's headline numbers use
64-node clusters; see BASELINE.md for the targets).

Usage: python benchmarks/scalability.py [--tasks N] [--actors N] [--pgs N]
"""
from __future__ import annotations

import argparse
import json
import time


def bench_many_tasks(n: int) -> dict:
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return 0

    ray_tpu.get([noop.remote() for _ in range(50)])  # warm worker pool
    t0 = time.perf_counter()
    ray_tpu.get([noop.remote() for _ in range(n)])
    dt = time.perf_counter() - t0
    return {"benchmark": "many_tasks", "n": n, "tasks_per_s": round(n / dt, 1)}


def bench_sequential_task_latency(n: int = 1000) -> dict:
    """1:1 sequential task round-trips — the per-task latency floor of
    the LEASE path (submit → push to the held lease → reply → get),
    reference microbenchmark: 'single client tasks sync'."""
    import ray_tpu

    @ray_tpu.remote
    def noop():
        return 0

    ray_tpu.get(noop.remote())  # lease + worker warm
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(noop.remote())
    dt = time.perf_counter() - t0
    return {
        "benchmark": "sequential_task_roundtrips",
        "n": n,
        "tasks_per_s": round(n / dt, 1),
        "p_latency_ms": round(dt / n * 1e3, 2),
    }


def bench_many_actors(n: int) -> dict:
    import ray_tpu

    # Fractional CPUs so actor count isn't capped by cores; the node's
    # worker-process cap (4x cores) is the real single-host ceiling.
    @ray_tpu.remote(num_cpus=0.05)
    class A:
        def ping(self):
            return 0

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(n)]
    ray_tpu.get([a.ping.remote() for a in actors])  # all alive + one call
    dt = time.perf_counter() - t0
    rate = n / dt
    for a in actors:
        ray_tpu.kill(a)
    return {"benchmark": "many_actors", "n": n, "actors_per_s": round(rate, 1)}


def bench_actor_call_throughput(calls: int) -> dict:
    import ray_tpu

    @ray_tpu.remote(max_concurrency=8)
    class A:
        def ping(self):
            return 0

    a = A.remote()
    ray_tpu.wait_actor_ready(a)
    ray_tpu.get([a.ping.remote() for _ in range(50)])
    t0 = time.perf_counter()
    ray_tpu.get([a.ping.remote() for _ in range(calls)])
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return {
        "benchmark": "async_actor_calls",
        "n": calls,
        "calls_per_s": round(calls / dt, 1),
    }


def bench_1to1_async_calls(calls: int) -> dict:
    """Single driver → single actor, fully pipelined (reference
    microbenchmark '1:1 async actor calls', ray_perf.py)."""
    import ray_tpu

    @ray_tpu.remote
    class A:
        def ping(self):
            return 0

    a = A.remote()
    ray_tpu.wait_actor_ready(a)
    ray_tpu.get([a.ping.remote() for _ in range(100)])
    t0 = time.perf_counter()
    refs = [a.ping.remote() for _ in range(calls)]
    ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    ray_tpu.kill(a)
    return {
        "benchmark": "1to1_async_actor_calls",
        "n": calls,
        "calls_per_s": round(calls / dt, 1),
    }


def bench_n_to_n_calls(n: int, calls: int) -> dict:
    """n caller processes each hammering their own actor (reference
    microbenchmark 'n:n async actor calls') — exercises the direct
    caller→actor transport from worker processes."""
    import ray_tpu

    @ray_tpu.remote
    class Target:
        def ping(self):
            return 0

    @ray_tpu.remote
    class Caller:
        def __init__(self, target):
            self.target = target

        def warmup(self):
            import ray_tpu as rt

            rt.get([self.target.ping.remote() for _ in range(50)])
            return 0

        def hammer(self, m: int) -> float:
            import time as _t

            import ray_tpu as rt

            t0 = _t.perf_counter()
            refs = [self.target.ping.remote() for _ in range(m)]
            rt.get(refs)
            return _t.perf_counter() - t0

    targets = [Target.remote() for _ in range(n)]
    callers = [Caller.remote(t) for t in targets]
    ray_tpu.get([c.warmup.remote() for c in callers])
    t0 = time.perf_counter()
    ray_tpu.get([c.hammer.remote(calls) for c in callers])
    wall = time.perf_counter() - t0
    for a in targets + callers:
        ray_tpu.kill(a)
    return {
        "benchmark": "n_to_n_async_actor_calls",
        "n_pairs": n,
        "calls_per_caller": calls,
        "calls_per_s": round(n * calls / wall, 1),
    }


def bench_small_object_get(n: int) -> dict:
    """Small-object get throughput (reference microbenchmark 'plasma
    get calls' ~10.3k/s): cold = uncached controller-directory gets;
    warm = owner-local memory-store hits."""
    import ray_tpu
    from ray_tpu.core.api import free

    refs = [ray_tpu.put(i) for i in range(n)]
    t0 = time.perf_counter()
    for r in refs:
        ray_tpu.get(r)
    cold = n / (time.perf_counter() - t0)
    one = refs[0]
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(one)
    warm = n / (time.perf_counter() - t0)
    free(refs)
    return {
        "benchmark": "small_object_get",
        "n": n,
        "cold_gets_per_s": round(cold, 1),
        "warm_gets_per_s": round(warm, 1),
    }


def bench_many_pgs(n: int) -> dict:
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group, remove_placement_group

    t0 = time.perf_counter()
    for _ in range(n):
        pg = placement_group([{"CPU": 0.01}], strategy="PACK")
        assert pg.ready(timeout=30)
        remove_placement_group(pg)
    dt = time.perf_counter() - t0
    return {"benchmark": "many_pgs", "n": n, "pg_create_remove_per_s": round(n / dt, 1)}


def bench_object_store(mb: int = 64, iters: int = 10) -> dict:
    import numpy as np

    import ray_tpu

    from ray_tpu.core.api import free

    data = np.zeros(mb * 1024 * 1024, dtype=np.uint8)
    ref = ray_tpu.put(data)  # warm
    ray_tpu.get(ref)
    free([ref])
    t0 = time.perf_counter()
    for _ in range(iters):
        r = ray_tpu.put(data)
        ray_tpu.get(r)
        # steady-state store bandwidth: freeing lets the arena reuse the
        # block, so iterations measure memcpy, not first-touch page faults
        # (the reference's plasma numbers likewise run on a warm arena)
        free([r])
    dt = time.perf_counter() - t0
    return {
        "benchmark": "object_store_put_get",
        "mb": mb,
        "gib_per_s": round(2 * mb * iters / 1024 / dt, 2),
    }


def main():
    import ray_tpu

    p = argparse.ArgumentParser()
    p.add_argument("--tasks", type=int, default=1000)
    p.add_argument("--actors", type=int, default=24)
    p.add_argument("--calls", type=int, default=1000)
    p.add_argument("--pgs", type=int, default=50)
    p.add_argument("--object-mb", type=int, default=64)
    p.add_argument("--direct-calls", type=int, default=20000)
    p.add_argument("--pairs", type=int, default=8)
    p.add_argument("--small-gets", type=int, default=3000)
    args = p.parse_args()

    ray_tpu.init(num_cpus=8)
    try:
        # Stream each result as it completes — a hang mid-suite must not
        # discard the lines already earned.
        for fn, fnargs in (
            (bench_many_tasks, (args.tasks,)),
            (bench_sequential_task_latency, (1000,)),
            (bench_many_actors, (args.actors,)),
            (bench_actor_call_throughput, (args.calls,)),
            (bench_1to1_async_calls, (args.direct_calls,)),
            (bench_n_to_n_calls, (args.pairs, args.direct_calls // 2)),
            (bench_small_object_get, (args.small_gets,)),
            (bench_many_pgs, (args.pgs,)),
            (bench_object_store, (args.object_mb,)),
        ):
            print(json.dumps(fn(*fnargs)), flush=True)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
