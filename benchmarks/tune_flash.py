"""Flash-attention block-size sweep on the real chip: times ONLY the
framework train step for the flagship 750M config under
RAY_TPU_FLASH_BLOCKS / RAY_TPU_FLASH_BWD_BLOCKS overrides.

Usage: python benchmarks/tune_flash.py "512,512" "1024,512" ...
       (each arg = "fwd_bq,fwd_bk[:bwd_bq,bwd_bk]")
"""
from __future__ import annotations

import os
import subprocess
import sys

CHILD = r"""
import os
import time
import jax
import jax.numpy as jnp
from ray_tpu.models import transformer as tf
from ray_tpu.parallel import MeshPlan, build_mesh, make_train_state, make_train_step
from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.train_step import make_optimizer

BATCH = int(os.environ.get("TUNE_BATCH", "8"))
D = int(os.environ.get("TUNE_D", "1536"))
L = int(os.environ.get("TUNE_L", "24"))
FF = int(os.environ.get("TUNE_FF", "4096"))
H = int(os.environ.get("TUNE_H", "12"))
cfg = tf.TransformerConfig(
    vocab_size=32000, d_model=D, n_layers=L, n_heads=H, n_kv_heads=H,
    d_ff=FF, max_seq_len=2048, dtype=jnp.bfloat16,
    remat=os.environ.get("TUNE_REMAT", "1") == "1",
    remat_policy=os.environ.get("TUNE_REMAT_POLICY", "full"),
    logits_chunk=int(os.environ.get("TUNE_LOGITS_CHUNK", "0")),
    scan_unroll=int(os.environ.get("TUNE_UNROLL", "1")),
)
plan = MeshPlan(dp=jax.device_count())
mesh = build_mesh(plan)
opt = make_optimizer(lr=3e-4, warmup=10)
params, opt_state, _ = make_train_state(cfg, plan, mesh, opt)
step = make_train_step(cfg, plan, mesh, opt)
tokens = jax.random.randint(jax.random.PRNGKey(1), (BATCH, 2049), 0, cfg.vocab_size)
batch = {"tokens": jax.device_put(tokens, mesh_lib.batch_sharding(mesh, plan))}
for _ in range(2):
    params, opt_state, m = step(params, opt_state, batch)
    print("warmup loss", float(m["loss"]), flush=True)
t0 = time.perf_counter()
N = 6
for _ in range(N):
    params, opt_state, m = step(params, opt_state, batch)
_ = float(m["loss"])  # materialize: forces the whole chain
dt = (time.perf_counter() - t0) / N
flops_tok = tf.flops_per_token(cfg, 2048)
n_params = sum(int(x.size) for x in jax.tree.leaves(params))
mfu = (flops_tok * BATCH * 2048 / dt) / (197e12 * jax.device_count())
tps = BATCH * 2048 / dt
print(f"RESULT {dt*1e3:.1f} ms/step  MFU {mfu:.2%}  {tps:.0f} tok/s  params {n_params/1e6:.0f}M", flush=True)
"""


def main():
    configs = sys.argv[1:] or ["512,512"]
    for spec in configs:
        if ":" in spec:
            fwd, bwd = spec.split(":")
        else:
            fwd, bwd = spec, ""
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["RAY_TPU_FLASH_BLOCKS"] = fwd
        if bwd:
            env["RAY_TPU_FLASH_BWD_BLOCKS"] = bwd
        else:
            env.pop("RAY_TPU_FLASH_BWD_BLOCKS", None)
        out = subprocess.run(
            [sys.executable, "-c", CHILD], env=env, capture_output=True, text=True,
            timeout=900,
        )
        line = [l for l in out.stdout.splitlines() if l.startswith("RESULT")]
        print(f"fwd={fwd} bwd={bwd or fwd}: {line[0][7:] if line else 'FAILED'}",
              flush=True)
        if not line:
            print(out.stderr[-500:], flush=True)


if __name__ == "__main__":
    main()
