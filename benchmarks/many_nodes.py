"""Control-plane scale: many nodes, deep task queue, many actors.

Reference: release/benchmarks/ many_nodes / many_tasks / many_actors
(README.md:1-16; 250-node task rate 351.4/s in release_logs). Here: N
real node-agent PROCESSES register with one controller; a deep queue of
tiny tasks and a burst of actors measure scheduler throughput, while a
side channel samples controller-loop latency (KV round-trips) under
load — the single-asyncio-loop design's health metric.

Usage: python benchmarks/many_nodes.py [--nodes 100] [--tasks 10000] [--actors 1000]
"""
from __future__ import annotations

import argparse
import json
import statistics
import threading
import time


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--nodes", type=int, default=100)
    p.add_argument("--tasks", type=int, default=10000)
    p.add_argument("--actors", type=int, default=1000)
    args = p.parse_args()

    import ray_tpu
    from ray_tpu.core.cluster_utils import Cluster

    cluster = Cluster({"CPU": 2})
    t0 = time.perf_counter()
    for i in range(args.nodes):
        cluster.add_node(num_cpus=1, resources={"filler": 4}, wait=False)
    # wait for all registrations
    deadline = time.monotonic() + 300
    cluster.connect()
    while time.monotonic() < deadline:
        alive = sum(1 for n in ray_tpu.nodes() if n["state"] == "ALIVE")
        if alive >= args.nodes + 1:
            break
        time.sleep(0.5)
    reg_time = time.perf_counter() - t0
    alive = sum(1 for n in ray_tpu.nodes() if n["state"] == "ALIVE")
    print(json.dumps({
        "benchmark": "many_nodes_register",
        "nodes": alive - 1,
        "seconds": round(reg_time, 1),
        "nodes_per_s": round((alive - 1) / reg_time, 1),
    }), flush=True)

    # controller-loop latency sampler (KV round-trips) during the storms
    lat: list = []
    stop = threading.Event()

    def sampler():
        core = ray_tpu.core.api._require_worker()
        while not stop.is_set():
            t = time.perf_counter()
            core.kv_get("bench", b"probe")
            lat.append(time.perf_counter() - t)
            time.sleep(0.05)

    sampler_thread = threading.Thread(target=sampler, daemon=True)
    sampler_thread.start()

    @ray_tpu.remote(num_cpus=1)
    def noop():
        return 0

    # warm a few workers
    ray_tpu.get([noop.remote() for _ in range(20)], timeout=300)
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(args.tasks)]
    ray_tpu.get(refs, timeout=1800)
    task_dt = time.perf_counter() - t0
    print(json.dumps({
        "benchmark": "many_nodes_tasks",
        "nodes": alive - 1,
        "tasks": args.tasks,
        "tasks_per_s": round(args.tasks / task_dt, 1),
    }), flush=True)

    @ray_tpu.remote(num_cpus=0.01)
    class A:
        def ping(self):
            return 0

    t0 = time.perf_counter()
    actors = [A.remote() for _ in range(args.actors)]
    ray_tpu.get([a.ping.remote() for a in actors], timeout=1800)
    actor_dt = time.perf_counter() - t0
    print(json.dumps({
        "benchmark": "many_nodes_actors",
        "actors": args.actors,
        "actors_per_s": round(args.actors / actor_dt, 1),
    }), flush=True)

    stop.set()
    sampler_thread.join(timeout=2)
    if lat:
        lat_ms = sorted(x * 1e3 for x in lat)
        print(json.dumps({
            "benchmark": "controller_loop_latency_under_load",
            "samples": len(lat_ms),
            "p50_ms": round(statistics.median(lat_ms), 1),
            "p99_ms": round(lat_ms[int(0.99 * (len(lat_ms) - 1))], 1),
            "max_ms": round(lat_ms[-1], 1),
        }), flush=True)

    for a in actors:
        ray_tpu.kill(a)
    ray_tpu.shutdown()
    cluster.shutdown()


if __name__ == "__main__":
    main()
