import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
def log(m):
    with open("/root/repo/.bench_tmp/rtt.log", "a") as f: f.write(m + "\n")
import jax, jax.numpy as jnp
from ray_tpu.models import transformer as tf
from ray_tpu.models.paged import PagedConfig, init_paged_cache, make_jitted
cfg = tf.TransformerConfig.llama7b(max_seq_len=2048, dtype=jnp.bfloat16, remat=False)
@jax.jit
def init_bf16(key):
    return jax.tree.map(lambda x: x.astype(jnp.bfloat16), tf.init_params(key, cfg))
params = init_bf16(jax.random.PRNGKey(0))
np.asarray(jax.tree.leaves(params)[0][0,0])
pcfg = PagedConfig(block_size=16, num_blocks=129, max_batch=16, max_blocks_per_seq=8)
cache = init_paged_cache(cfg, pcfg)
toks = jnp.zeros(16, jnp.int32); tables = jnp.asarray(np.arange(1,129).reshape(16,8).astype(np.int32))
lens = jnp.zeros(16, jnp.int32); temps = jnp.zeros(16, jnp.float32); key = jax.random.PRNGKey(0)
dec, pf = make_jitted(cfg)
out, cache = dec(params, toks, cache, tables, lens, temps, key)
np.asarray(out)
# synced per step
t0 = time.perf_counter()
for _ in range(16):
    out, cache = dec(params, out, cache, tables, lens, temps, key)
    np.asarray(out)
log(f"synced per step: {(time.perf_counter()-t0)/16*1000:.1f} ms/step")
# chained, one sync
t0 = time.perf_counter()
for _ in range(16):
    out, cache = dec(params, out, cache, tables, lens, temps, key)
np.asarray(out)
log(f"chained 16 + 1 sync: {(time.perf_counter()-t0)/16*1000:.1f} ms/step")
# pure RTT: tiny transfer
x = jnp.zeros(4, jnp.int32)
t0 = time.perf_counter()
for _ in range(10):
    np.asarray(x + 1)
log(f"tiny roundtrip: {(time.perf_counter()-t0)/10*1000:.1f} ms")
