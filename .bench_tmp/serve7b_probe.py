import sys
sys.path.insert(0, "/root/repo")
import time

LOG = "/root/repo/.bench_tmp/serve7b.log"


def log(m):
    with open(LOG, "a") as f:
        f.write(f"[{time.strftime('%H:%M:%S')}] {m}\n")


log("start")
import jax
import jax.numpy as jnp

from ray_tpu.models import transformer as tf
from ray_tpu.models.paged import PagedConfig, init_paged_cache, make_jitted

cfg = tf.TransformerConfig.llama7b(max_seq_len=2048, dtype=jnp.bfloat16, remat=False)


@jax.jit
def init_bf16(key):
    return jax.tree.map(lambda x: x.astype(jnp.bfloat16), tf.init_params(key, cfg))


t0 = time.perf_counter()
params = init_bf16(jax.random.PRNGKey(0))
jax.block_until_ready(jax.tree.leaves(params)[0])
log(f"params ready {time.perf_counter()-t0:.0f}s")
pcfg = PagedConfig(block_size=16, num_blocks=129, max_batch=16, max_blocks_per_seq=8)
cache = init_paged_cache(cfg, pcfg)
jax.block_until_ready(cache["k"])
log("cache ready")
toks = jnp.zeros(16, jnp.int32)
tables = jnp.zeros((16, 8), jnp.int32)
lens = jnp.zeros(16, jnp.int32)
temps = jnp.zeros(16, jnp.float32)
key = jax.random.PRNGKey(0)
dec, pf = make_jitted(cfg)
t0 = time.perf_counter()
lowered = dec.lower(params, toks, cache, tables, lens, temps, key)
log(f"decode lowered {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
compiled = lowered.compile()
log(f"decode compiled {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
out, cache = compiled(params, toks, cache, tables, lens, temps, key)
jax.block_until_ready(out)
log(f"decode step1 {time.perf_counter()-t0:.2f}s")
t0 = time.perf_counter()
for _ in range(16):
    out, cache = compiled(params, out, cache, tables, lens, temps, key)
jax.block_until_ready(out)
log(f"decode steady {(time.perf_counter()-t0)/16*1000:.1f}ms/step")
ptoks = jnp.zeros((1, 32), jnp.int32)
row = jnp.zeros(2, jnp.int32)
t0 = time.perf_counter()
pl = pf.lower(params, ptoks, cache, row, 16, jnp.int32(32), jnp.float32(0.0), key)
log(f"prefill lowered {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
pc = pl.compile()
log(f"prefill compiled {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
tok, cache = pc(params, ptoks, cache, row, jnp.int32(32), jnp.float32(0.0), key)
jax.block_until_ready(tok)
log(f"prefill step {time.perf_counter()-t0:.2f}s")
log("DONE")
