import sys; sys.path.insert(0, "/root/repo")
import time
def log(m):
    with open("/root/repo/.bench_tmp/mem.log", "a") as f: f.write(m + "\n")
import jax, jax.numpy as jnp
from ray_tpu.models import transformer as tf
from ray_tpu.models.paged import PagedConfig, init_paged_cache, make_jitted
cfg = tf.TransformerConfig.llama7b(max_seq_len=2048, dtype=jnp.bfloat16, remat=False)
pcfg = PagedConfig(block_size=16, num_blocks=129, max_batch=16, max_blocks_per_seq=8)
dec, pf = make_jitted(cfg, 8)
# memory analysis WITHOUT allocating the real params: AOT lower+compile on shapes
import numpy as np
shapes = jax.eval_shape(lambda k: tf.init_params(k, cfg), jax.random.PRNGKey(0))
params_s = jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16), shapes)
cache_s = jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), jax.eval_shape(lambda: init_paged_cache(cfg, pcfg)))
toks = jax.ShapeDtypeStruct((16,), jnp.int32); tables = jax.ShapeDtypeStruct((16,8), jnp.int32)
lens = jax.ShapeDtypeStruct((16,), jnp.int32); temps = jax.ShapeDtypeStruct((16,), jnp.float32)
key = jax.ShapeDtypeStruct((2,), jnp.uint32)
t0=time.perf_counter()
lowered = dec.lower(params_s, toks, cache_s, tables, lens, temps, key)
log(f"lowered {time.perf_counter()-t0:.1f}s")
t0=time.perf_counter()
compiled = lowered.compile()
log(f"compiled {time.perf_counter()-t0:.1f}s")
ma = compiled.memory_analysis()
log(f"memory: {ma}")
