import sys
sys.path.insert(0, "/root/repo")
import bench
def log(m):
    with open("/root/repo/.bench_tmp/serve_bench.log", "a") as f:
        f.write(m + "\n")
r = bench._bench_serving_7b(log)
log(f"RESULT {r}")
