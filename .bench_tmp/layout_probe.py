import sys; sys.path.insert(0, "/root/repo")
import time
def log(m):
    with open("/root/repo/.bench_tmp/layout.log", "a") as f: f.write(m + "\n")
import jax, jax.numpy as jnp
from jax.experimental.layout import Format, Layout
from ray_tpu.models import transformer as tf
from ray_tpu.models.paged import PagedConfig, init_paged_cache, paged_decode_loop
cfg = tf.TransformerConfig.llama7b(max_seq_len=2048, dtype=jnp.bfloat16, remat=False)
pcfg = PagedConfig(block_size=16, num_blocks=73, max_batch=16, max_blocks_per_seq=8)
def _decode(params, tokens, cache, tables, lens, temps, key):
    return paged_decode_loop(params, cfg, tokens, cache, tables, lens, temps, key, 8)
shapes = jax.eval_shape(lambda k: tf.init_params(k, cfg), jax.random.PRNGKey(0))
params_s = jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, jnp.bfloat16), shapes)
cache_s = jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), jax.eval_shape(lambda: init_paged_cache(cfg, pcfg)))
toks = jax.ShapeDtypeStruct((16,), jnp.int32); tables = jax.ShapeDtypeStruct((16,8), jnp.int32)
lens = jax.ShapeDtypeStruct((16,), jnp.int32); temps = jax.ShapeDtypeStruct((16,), jnp.float32)
key = jax.ShapeDtypeStruct((2,), jnp.uint32)
auto = Format(Layout.AUTO)
params_auto = jax.tree.map(lambda _: auto, params_s)
dec = jax.jit(_decode, donate_argnums=(2,), in_shardings=(params_auto, None, None, None, None, None, None))
t0=time.perf_counter()
compiled = dec.lower(params_s, toks, cache_s, tables, lens, temps, key).compile()
log(f"compiled {time.perf_counter()-t0:.1f}s")
ma = compiled.memory_analysis()
log(f"temp={ma.temp_size_in_bytes/1e9:.2f}GB args={ma.argument_size_in_bytes/1e9:.2f}GB out={ma.output_size_in_bytes/1e9:.2f}GB alias={ma.alias_size_in_bytes/1e9:.2f}GB")
fmts = compiled.input_formats
log(f"wq format: {jax.tree.leaves(fmts)[:1]}")
